"""AOT-exported program bank (examl_tpu/ops/export_bank.py): the
fallback-not-crash load ladder, the corrupt-artifact rejection matrix
with quarantine semantics, the `bank.export.*` fault points, and the
zero-compile cold-start/restart integration with the CLI and `--bank`
(run 2 of an identical run serves its first result with
`engine.compile_count == 0` and `bank.export.hits > 0`)."""

import hashlib
import json
import os
import pickle
import types

import numpy as np
import pytest

from tests.conftest import correlated_dna

from examl_tpu import config, obs
from examl_tpu.ops import bank, export_bank
from examl_tpu.resilience import faults


# ---------------------------------------------------------------------------
# fixtures / helpers


@pytest.fixture
def export_env(tmp_path, monkeypatch):
    """Isolated persistent cache + export bank ON; restores the real
    cache config afterwards (follows test_bank.py's isolation pattern:
    artifacts and manifests must never land in the real user cache)."""
    monkeypatch.setenv("EXAML_COMPILE_CACHE", str(tmp_path / "xla"))
    monkeypatch.setenv("EXAML_EXPORT_BANK", "on")
    cache = config.enable_persistent_compilation_cache()
    assert cache, "persistent cache must enable for export-bank tests"
    export_bank.reset()
    faults.reset()
    obs.reset()
    yield cache
    export_bank.reset()
    monkeypatch.delenv("EXAML_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("EXAML_EXPORT_BANK", raising=False)
    config.enable_persistent_compilation_cache()     # re-point jax


def _toy_program():
    """A small donating jit program with the same shape of seams the
    engine programs have (scan + dot + donated carry)."""
    import jax
    import jax.numpy as jnp

    def impl(x, y):
        def body(c, _):
            return c @ y + 1.0, None
        c, _ = jax.lax.scan(body, x, None, length=4)
        return c, c.sum()

    raw = jax.jit(impl, donate_argnums=(0,))
    x = jnp.ones((16, 16))
    y = jnp.eye(16) * 0.5
    return raw, x, y


def _boom(*args):
    raise AssertionError("fallback dispatched — the exported artifact "
                         "was not served")


def _populate(static_key=("toy", 0)):
    """Export one toy artifact via the real miss path; returns the
    expected result and the artifact signature."""
    import jax.numpy as jnp
    raw, x, y = _toy_program()
    wrapped = export_bank.wrap(raw, raw, "toy", static_key)
    out = wrapped(jnp.array(np.asarray(x)), y)
    exports = export_bank.read_exports()
    assert len(exports) == 1, exports
    (sig, entry), = exports.items()
    return np.asarray(out[0]), float(out[1]), sig, entry


# ---------------------------------------------------------------------------
# mode / signature units


def test_mode_parsing(monkeypatch):
    for v, want in (("", "off"), ("0", "off"), ("off", "off"),
                    ("1", "on"), ("on", "on"), ("require", "require")):
        monkeypatch.setenv(export_bank.ENV_VAR, v)
        assert export_bank.mode() == want
    monkeypatch.setenv(export_bank.ENV_VAR, "frobnicate")
    with pytest.raises(ValueError):
        export_bank.mode()
    monkeypatch.delenv(export_bank.ENV_VAR, raising=False)
    assert export_bank.mode() == "off"                # opt-in default


def test_wrap_off_mode_returns_fallback_unchanged(monkeypatch):
    monkeypatch.delenv(export_bank.ENV_VAR, raising=False)
    raw, _, _ = _toy_program()
    sentinel = object()
    assert export_bank.wrap(raw, sentinel, "toy", ("k",)) is sentinel
    # Ineligible programs bypass the bank even when it is on.
    monkeypatch.setenv(export_bank.ENV_VAR, "on")
    assert export_bank.wrap(raw, sentinel, "toy", ("k",),
                            exportable=False) is sentinel


def test_signature_is_stable_and_key_sensitive():
    import jax.numpy as jnp
    args = (jnp.ones((4, 2)), None, 3)
    rkey = export_bank._route_key(args)
    rkey2 = export_bank._route_key((jnp.zeros((4, 2)), None, 7))
    assert rkey == rkey2                     # avals, not values
    assert export_bank.signature("k1", rkey) == \
        export_bank.signature("k1", rkey2)
    assert export_bank.signature("k1", rkey) != \
        export_bank.signature("k2", rkey)    # static key disambiguates
    rkey3 = export_bank._route_key((jnp.ones((4, 3)), None, 3))
    assert export_bank.signature("k1", rkey) != \
        export_bank.signature("k1", rkey3)   # shape disambiguates


# ---------------------------------------------------------------------------
# export -> load round trip


def test_roundtrip_export_then_load(export_env):
    import jax.numpy as jnp
    ref_arr, ref_sum, sig, entry = _populate()
    c = obs.snapshot_counters()
    assert c["bank.export.misses"] == 1
    assert c["bank.export.writes"] == 1
    assert c.get("bank.export.write_errors", 0) == 0
    d = export_bank.bank_dir()
    path = os.path.join(d, entry["file"])
    assert os.path.exists(path)
    assert entry["digest"] == hashlib.sha256(
        open(path, "rb").read()).hexdigest()
    assert entry["abi"] == export_bank.EXPORT_ABI
    import jax
    import jaxlib
    assert entry["jax"] == jax.__version__
    assert entry["jaxlib"] == jaxlib.__version__

    # Cold process emulation: memos dropped, fresh jit object, a
    # fallback that EXPLODES if dispatched — the artifact must serve.
    export_bank.reset()
    obs.reset()
    raw, x, y = _toy_program()
    wrapped = export_bank.wrap(raw, _boom, "toy", ("toy", 0))
    out = wrapped(jnp.array(np.asarray(x)), y)
    assert float(out[1]) == ref_sum
    np.testing.assert_array_equal(np.asarray(out[0]), ref_arr)
    c = obs.snapshot_counters()
    assert c["bank.export.hits"] == 1
    assert c.get("bank.export.misses", 0) == 0
    # Second call reuses the installed route (no second load).
    out2 = wrapped(jnp.array(np.asarray(x)), y)
    assert float(out2[1]) == ref_sum
    assert obs.snapshot_counters()["bank.export.hits"] == 1
    t = obs.snapshot()["timers"].get("bank.export_load_seconds")
    assert t and t["count"] == 1


def test_require_mode_serves_hits_and_raises_on_miss(export_env,
                                                    monkeypatch):
    import jax.numpy as jnp
    _, ref_sum, _, _ = _populate()
    export_bank.reset()
    monkeypatch.setenv(export_bank.ENV_VAR, "require")
    raw, x, y = _toy_program()
    wrapped = export_bank.wrap(raw, _boom, "toy", ("toy", 0))
    assert float(wrapped(jnp.array(np.asarray(x)), y)[1]) == ref_sum
    # A signature with no artifact must hard-fail, not silently compile.
    other = export_bank.wrap(raw, raw, "toy", ("toy", "novel"))
    with pytest.raises(export_bank.ExportBankRequired):
        other(jnp.array(np.asarray(x)), y)


# ---------------------------------------------------------------------------
# corrupt-artifact matrix: every failure mode degrades with the right
# counter, quarantines, and the run still completes


def _mutate_manifest(sig, **fields):
    path = export_bank._manifest_path()
    doc = json.load(open(path))
    doc["exports"][sig].update(fields)
    with open(path, "w") as f:
        json.dump(doc, f)


def _reload_after_corruption():
    """Fresh wrapper + memo reset; returns (result_sum, counters)."""
    import jax.numpy as jnp
    export_bank.reset()
    obs.reset()
    raw, x, y = _toy_program()
    wrapped = export_bank.wrap(raw, raw, "toy", ("toy", 0))
    out = wrapped(jnp.array(np.asarray(x)), y)
    return float(out[1]), obs.snapshot_counters()


@pytest.mark.parametrize("case", ["truncated", "flipped_digest",
                                  "wrong_jax", "wrong_fingerprint",
                                  "stale_entry", "garbage_payload"])
def test_corrupt_artifact_matrix(export_env, case):
    _, ref_sum, sig, entry = _populate()
    d = export_bank.bank_dir()
    path = os.path.join(d, entry["file"])

    if case == "truncated":
        blob = open(path, "rb").read()
        open(path, "wb").write(blob[:len(blob) // 2])
        want, quarantined = "bank.export.rejected.digest", True
    elif case == "flipped_digest":
        _mutate_manifest(sig, digest="0" * 64)
        want, quarantined = "bank.export.rejected.digest", True
    elif case == "wrong_jax":
        _mutate_manifest(sig, jax="0.0.1")
        want, quarantined = "bank.export.rejected.version", True
    elif case == "wrong_fingerprint":
        _mutate_manifest(sig, fingerprint="deadbeef0000")
        want, quarantined = "bank.export.rejected.fingerprint", True
    elif case == "stale_entry":
        os.unlink(path)
        want, quarantined = "bank.export.rejected.missing", False
    elif case == "garbage_payload":
        garbage = pickle.dumps({"payload": b"not an executable",
                                "in_tree": None, "out_tree": None})
        open(path, "wb").write(garbage)
        _mutate_manifest(sig, digest=hashlib.sha256(garbage).hexdigest())
        want, quarantined = "bank.export.corrupt", True

    # Restart 1: the bad artifact is rejected with ITS counter, the
    # program falls through to a compile, the run completes — and the
    # miss path re-exports a healthy replacement.
    got_sum, c = _reload_after_corruption()
    assert got_sum == ref_sum
    assert c.get(want, 0) == 1, (case, c)
    assert c.get("bank.export.hits", 0) == 0
    assert os.path.exists(path + export_bank.QUARANTINE_SUFFIX) \
        == quarantined
    if quarantined:
        assert c.get("bank.export.quarantined", 0) == 1
    assert c.get("bank.export.writes", 0) == 1   # healed by re-export
    # Restart 2: the quarantined artifact CANNOT re-fail — the fresh
    # replacement serves a clean hit, zero rejections.
    got_sum2, c2 = _reload_after_corruption()
    assert got_sum2 == ref_sum
    assert c2.get(want, 0) == 0, (case, c2)
    assert c2.get("bank.export.hits", 0) == 1
    assert c2.get("bank.export.quarantined", 0) == 0


# ---------------------------------------------------------------------------
# fault points (GL006: survivable, :after=N grammar)


def test_fault_export_write_is_survivable(export_env, monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setenv("EXAML_FAULTS", "bank.export.write")
    faults.reset()
    raw, x, y = _toy_program()
    wrapped = export_bank.wrap(raw, raw, "toy", ("toy", 0))
    out = wrapped(jnp.array(np.asarray(x)), y)     # must not raise
    c = obs.snapshot_counters()
    assert c["bank.export.write_errors"] == 1
    assert c["faults.fired.bank.export.write"] == 1
    assert not export_bank.read_exports()          # no artifact
    del out


def test_fault_export_write_after_n_grammar(export_env, monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setenv("EXAML_FAULTS", "bank.export.write:after=2")
    faults.reset()
    raw, x, y = _toy_program()
    w1 = export_bank.wrap(raw, raw, "toy", ("toy", 0))
    w1(jnp.array(np.asarray(x)), y)                # write 1: survives
    assert len(export_bank.read_exports()) == 1
    w2 = export_bank.wrap(raw, raw, "toy", ("toy", 1))
    w2(jnp.array(np.asarray(x)), y)                # write 2: injected
    c = obs.snapshot_counters()
    assert c["bank.export.writes"] == 1
    assert c["bank.export.write_errors"] == 1
    assert len(export_bank.read_exports()) == 1


def test_fault_export_load_is_survivable(export_env, monkeypatch):
    import jax.numpy as jnp
    _, ref_sum, sig, entry = _populate()
    export_bank.reset()
    obs.reset()
    monkeypatch.setenv("EXAML_FAULTS", "bank.export.load")
    faults.reset()
    raw, x, y = _toy_program()
    wrapped = export_bank.wrap(raw, raw, "toy", ("toy", 0))
    out = wrapped(jnp.array(np.asarray(x)), y)     # falls through
    assert float(out[1]) == ref_sum
    c = obs.snapshot_counters()
    assert c["bank.export.rejected.error"] == 1
    assert c["faults.fired.bank.export.load"] == 1
    # Environment fault, not a bad artifact: NOT quarantined, and the
    # next (un-faulted) restart serves it.
    d = export_bank.bank_dir()
    assert os.path.exists(os.path.join(d, entry["file"]))
    monkeypatch.delenv("EXAML_FAULTS", raising=False)
    faults.reset()
    export_bank.reset()
    obs.reset()
    w2 = export_bank.wrap(raw, _boom, "toy", ("toy", 0))
    assert float(w2(jnp.array(np.asarray(x)), y)[1]) == ref_sum
    assert obs.snapshot_counters()["bank.export.hits"] == 1


# ---------------------------------------------------------------------------
# run_bank integration: exported coverage skips compile workers


def test_family_coverage_prebackend_scan(tmp_path, monkeypatch):
    """Coverage must be computable BEFORE the backend initializes (the
    bank's ordering contract): with no cache dir configured in jax, the
    root scan finds entries whose backend-independent stamps match."""
    import jax
    import jaxlib
    root = tmp_path / "xroot"
    part = root / "cpu-fake-partition"
    part.mkdir(parents=True)
    fp = config.host_feature_fingerprint() or ""
    ok = {"family": "fast", "abi": export_bank.EXPORT_ABI,
          "jax": jax.__version__, "jaxlib": jaxlib.__version__,
          "fingerprint": fp, "file": "a.jexe", "digest": "x",
          "ntips": 8}
    stale = dict(ok, family="grad", jax="0.0.1")
    other_host = dict(ok, family="universal", fingerprint="feedface0bad")
    other_data = dict(ok, family="traverse", ntips=50)
    (part / "bank_manifest.json").write_text(json.dumps(
        {"exports": {"s1": ok, "s2": stale, "s3": other_host,
                     "s4": other_data}}))
    monkeypatch.setenv("EXAML_COMPILE_CACHE", str(root))
    monkeypatch.setenv("EXAML_EXPORT_BANK", "on")
    monkeypatch.setattr(config, "persistent_cache_dir", lambda: None)
    cover = export_bank.family_coverage()
    assert cover == {"fast": 1, "traverse": 1}   # no ntaxa: no filter
    # Dataset guard: another dataset's artifacts (ntips mismatch) must
    # not count as coverage — name-level skip would lose the compile
    # workers only to miss at warm time.
    assert export_bank.family_coverage(ntaxa=8) == {"fast": 1}
    assert export_bank.family_coverage(["grad"]) == {}


def test_run_bank_skips_workers_for_covered_families(tmp_path,
                                                     monkeypatch):
    """Every enumerated family exported-covered -> run_bank spawns NO
    compile workers, marks the families 'exported', counts
    bank.exported_families and joins them to the banked set."""
    import jax
    import jaxlib
    monkeypatch.setenv("EXAML_EXPORT_BANK", "on")
    fams = bank.enumerate_families("e")
    fp = config.host_feature_fingerprint() or ""
    exports = {f"sig{i}": {"family": f, "abi": export_bank.EXPORT_ABI,
                           "jax": jax.__version__,
                           "jaxlib": jaxlib.__version__,
                           "fingerprint": fp, "file": f"{f}.jexe",
                           "digest": "x"}
               for i, f in enumerate(fams)}
    root = tmp_path / "xroot"
    part = root / "cpu-part"
    part.mkdir(parents=True)
    (part / "bank_manifest.json").write_text(
        json.dumps({"exports": exports}))
    monkeypatch.setenv("EXAML_COMPILE_CACHE", str(root))
    monkeypatch.setattr(config, "persistent_cache_dir", lambda: None)
    obs.reset()
    args = types.SimpleNamespace(bytefile="unused.binary",
                                 compile_timeout=5.0, mode="e",
                                 model="GAMMA", save_memory=False)
    logs = []
    report = bank.run_bank(args, log=logs.append)
    assert set(report) == set(fams)
    assert all(r["status"] == "exported" for r in report.values())
    c = obs.snapshot_counters()
    assert c["bank.exported_families"] == len(fams)
    assert c.get("bank.no_cache", 0) == 0      # no-worker run: no scare
    assert all(bank.is_banked(f) for f in fams)
    assert any("no compile workers spawned" in m for m in logs)
    bank.reset()


# ---------------------------------------------------------------------------
# CLI cold start: run 2 serves with zero first-call compiles
# (the fast in-process representative; the SIGKILL supervisor variant
# is the -m slow e2e below, and CI's coldstart-smoke measures the
# >=10x wall-clock claim in real subprocesses)


def _tiny_cli_fixture(tmp_path, seed=5):
    from examl_tpu.instance import PhyloInstance
    from examl_tpu.io.bytefile import write_bytefile

    data = correlated_dna(8, 120, seed=7)
    bf = str(tmp_path / "tiny.binary")
    write_bytefile(bf, data)
    tree = PhyloInstance(data).random_tree(seed)
    tf = str(tmp_path / "tiny.tree")
    open(tf, "w").write(tree.to_newick(data.taxon_names))
    return bf, tf


def test_cli_cold_start_zero_compiles(tmp_path, monkeypatch):
    """Acceptance-shaped: two identical -f e runs against the same
    workdir/cache; run 1 populates the exported bank, run 2 (cold
    process state) serves its result with engine.compile_count == 0 and
    bank.export.hits > 0, at an identical likelihood."""
    from examl_tpu.cli.main import main

    monkeypatch.setenv("EXAML_COMPILE_TIMEOUT", "180")   # restore after
    monkeypatch.setenv("EXAML_COMPILE_CACHE", str(tmp_path / "xla"))
    monkeypatch.setenv("EXAML_EXPORT_BANK", "on")
    bf, tf = _tiny_cli_fixture(tmp_path)
    m1, m2 = str(tmp_path / "m1.json"), str(tmp_path / "m2.json")
    base = ["-s", bf, "-t", tf, "-f", "e", "-w", str(tmp_path / "out"),
            "--single-device"]
    try:
        assert main(base + ["-n", "CS1", "--metrics", m1]) == 0
        assert main(base + ["-n", "CS2", "--metrics", m2]) == 0
    finally:
        monkeypatch.delenv("EXAML_COMPILE_CACHE", raising=False)
        config.enable_persistent_compilation_cache()     # re-point jax
    c1 = json.load(open(m1))["counters"]
    c2 = json.load(open(m2))["counters"]
    assert c1["engine.compile_count"] > 0                # cold populate
    assert c1["bank.export.writes"] >= 3
    assert c1.get("bank.export.write_errors", 0) == 0
    # THE acceptance line: the restarted run never compiles.
    assert c2.get("engine.compile_count", 0) == 0, c2
    assert c2["bank.export.hits"] >= 3
    assert c2.get("bank.export.rejected.error", 0) == 0
    assert c2.get("bank.export.corrupt", 0) == 0
    # Identical result: the exported path runs the same programs.
    info1 = open(tmp_path / "out" / "ExaML_info.CS1").read()
    info2 = open(tmp_path / "out" / "ExaML_info.CS2").read()
    lnl1 = [ln for ln in info1.splitlines() if "Likelihood tree" in ln]
    lnl2 = [ln for ln in info2.splitlines() if "Likelihood tree" in ln]
    assert lnl1 and lnl1 == lnl2


@pytest.mark.slow          # supervised SIGKILL e2e (~2-3 min): the
                           # resumed attempt must load from the
                           # exported bank instead of recompiling
def test_supervised_sigkill_resumes_from_exported_bank(tmp_path,
                                                       monkeypatch):
    from examl_tpu.cli.main import main

    monkeypatch.setenv("EXAML_COMPILE_TIMEOUT", "300")   # restore after
    monkeypatch.setenv("EXAML_COMPILE_CACHE", str(tmp_path / "xla"))
    monkeypatch.setenv("EXAML_EXPORT_BANK", "on")
    bf, tf = _tiny_cli_fixture(tmp_path)
    m = str(tmp_path / "m.json")
    try:
        rc = main(["-s", bf, "-n", "SKX", "-t", tf, "-f", "d", "-i",
                   "5", "-w", str(tmp_path / "out"), "--bank",
                   "--supervise", "--supervise-backoff", "0.2",
                   "--supervise-retries", "3",
                   "--metrics", m, "--single-device",
                   "--inject-fault", "search.kill:after=12"])
    finally:
        monkeypatch.delenv("EXAML_COMPILE_CACHE", raising=False)
        config.enable_persistent_compilation_cache()     # re-point jax
    assert rc == 0
    snap = json.load(open(m))
    c = snap["counters"]
    assert c["resilience.restarts"] >= 1                 # it crashed
    # The resumed attempt deserialized instead of recompiling: export
    # hits in its snapshot, and its bank phase skipped covered
    # families' compile workers.
    assert c.get("bank.export.hits", 0) > 0, c
    assert c.get("bank.exported_families", 0) > 0, c
    # Ledger evidence on the merged timeline (the ledger lives next to
    # the --metrics file): the resumed attempt's export hits exist (and
    # quarantine/corruption did not occur).
    from examl_tpu.obs import ledger as _ledger
    evs = _ledger.read_dir(str(tmp_path))
    hits = [e for e in evs if e.get("kind") == "export"
            and e.get("status") == "hit"]
    assert hits
    assert c.get("bank.export.corrupt", 0) == 0
    assert os.path.exists(tmp_path / "out" / "ExaML_result.SKX")
