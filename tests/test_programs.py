"""Program observatory (examl_tpu/obs/programs.py): the analysis-
availability matrix (partial / empty / raising XLA analyses degrade to
`program.analysis_missing.*` counters, never a crash), the registry /
stream / snapshot-embed plumbing, the model-vs-compiler drift gate,
live memory sampling, and the run_report snapshot diff."""

import json
import os
import sys

import numpy as np
import pytest

from examl_tpu import obs
from examl_tpu.obs import programs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_observatory(monkeypatch):
    """Each test starts with an empty registry and the default knobs
    (the observatory is process-global by design)."""
    monkeypatch.delenv("EXAML_PROGRAM_OBS", raising=False)
    monkeypatch.delenv("EXAML_DRIFT_TOL_PCT", raising=False)
    monkeypatch.delenv("EXAML_LEDGER_DIR", raising=False)
    monkeypatch.setenv("EXAML_MEM_SAMPLE_S", "0")
    programs.reset()
    yield
    programs.reset()
    # Scrub fake-device gauges out of the process-global registry: a
    # stale 1-byte mem.device.*.in_use would poison the memory
    # governor's usage signal for every later in-process test.
    reg = obs.registry()
    with reg._lock:
        for k in [k for k in reg._gauges if k.startswith("mem.device.")]:
            del reg._gauges[k]


def _counter(name):
    return obs.registry().counter(name)


# -- fakes spanning the analysis-availability matrix -------------------------


class FakeMem:
    def __init__(self, arg=100, out=50, temp=25, peak=None):
        if arg is not None:
            self.argument_size_in_bytes = arg
        if out is not None:
            self.output_size_in_bytes = out
        if temp is not None:
            self.temp_size_in_bytes = temp
        if peak is not None:
            self.peak_memory_in_bytes = peak


class FakeCompiled:
    """cost= list-of-dicts (jaxlib's shape), a plain dict, None, [] or
    an exception instance (raised); mem= FakeMem, None or exception;
    text= optimized-HLO text for the collective census (default: one
    all-reduce, the fabric's invariant shape) or an exception."""

    _HLO = 'ar = f64[] all-reduce(f64[] x), replica_groups={}'

    def __init__(self, cost=None, mem=None, text=_HLO):
        self._cost, self._mem, self._text = cost, mem, text

    def cost_analysis(self):
        if isinstance(self._cost, Exception):
            raise self._cost
        return self._cost

    def memory_analysis(self):
        if isinstance(self._mem, Exception):
            raise self._mem
        return self._mem

    def as_text(self):
        if isinstance(self._text, Exception):
            raise self._text
        return self._text


class FakeLowered:
    def __init__(self, compiled):
        self._compiled = compiled
        self.compile_calls = 0

    def compile(self):
        self.compile_calls += 1
        if isinstance(self._compiled, Exception):
            raise self._compiled
        return self._compiled


# -- the matrix --------------------------------------------------------------


def test_record_full_analyses_populates_row_and_gauges():
    compiled = FakeCompiled(
        cost=[{"flops": 1e6, "bytes accessed": 4e5,
               "transcendentals": 300.0}],
        mem=FakeMem(arg=100, out=50, temp=25))
    row = programs.record("fast", ("fast", 1, 2), "fresh", 1.5,
                          compiled=compiled)
    assert row["family"] == "fast" and row["source"] == "fresh"
    assert row["flops"] == 1e6 and row["bytes_accessed"] == 4e5
    assert row["transcendentals"] == 300.0
    assert (row["argument_bytes"], row["output_bytes"],
            row["temp_bytes"]) == (100, 50, 25)
    assert row["peak_bytes"] == 175          # structural: arg+out+temp
    assert row["collectives"] == {"all-reduce": 1}
    assert row["collective_total"] == 1
    assert "missing" not in row
    snap = obs.registry().snapshot_light()
    assert snap["gauges"]["program.count"] == 1
    assert snap["gauges"]["program.bytes_accessed.fast"] == 4e5
    assert snap["gauges"]["program.flops.fast"] == 1e6
    assert snap["gauges"]["program.peak_bytes.fast"] == 175
    assert snap["gauges"]["program.collectives.fast"] == 1
    assert [r["family"] for r in programs.table()] == ["fast"]


def test_record_dict_cost_and_explicit_peak_win():
    row = programs.record(
        "scan", "k", "xla-cache", 0.2,
        compiled=FakeCompiled(cost={"flops": 5.0,
                                    "bytes_accessed": 7.0,
                                    "transcendentals": 1.0},
                              mem=FakeMem(peak=9999)))
    assert row["bytes_accessed"] == 7.0      # underscore key accepted
    assert row["peak_bytes"] == 9999         # explicit attr beats sum


@pytest.mark.parametrize("cost", [None, [], Exception("boom")])
def test_cost_analysis_unavailable_counts_not_crashes(cost):
    c0 = _counter("program.analysis_missing.cost_analysis")
    row = programs.record("fast", "k", "fresh", 0.1,
                          compiled=FakeCompiled(cost=cost,
                                                mem=FakeMem()))
    assert row is not None and "bytes_accessed" not in row
    assert _counter("program.analysis_missing.cost_analysis") == c0 + 1
    assert "cost_analysis" in row["missing"]
    assert row["peak_bytes"] == 175          # memory side still lands


def test_memory_analysis_unavailable_counts_not_crashes():
    c0 = _counter("program.analysis_missing.memory_analysis")
    row = programs.record(
        "fast", "k", "fresh", 0.1,
        compiled=FakeCompiled(cost=[{"flops": 1.0}],
                              mem=Exception("no mem analysis")))
    assert row["flops"] == 1.0 and "peak_bytes" not in row
    assert _counter("program.analysis_missing.memory_analysis") == c0 + 1


def test_partial_analyses_count_each_missing_field():
    c_b = _counter("program.analysis_missing.bytes_accessed")
    c_t = _counter("program.analysis_missing.temp_bytes")
    row = programs.record(
        "fast", "k", "fresh", 0.1,
        compiled=FakeCompiled(cost=[{"flops": 2.0}],       # no bytes key
                              mem=FakeMem(temp=None)))     # no temp attr
    assert row["flops"] == 2.0 and "bytes_accessed" not in row
    assert _counter("program.analysis_missing.bytes_accessed") == c_b + 1
    assert _counter("program.analysis_missing.temp_bytes") == c_t + 1
    assert row["peak_bytes"] == 150          # peak from the fields present
    assert set(row["missing"]) >= {"bytes_accessed", "temp_bytes"}


def test_record_never_raises_on_hostile_compiled():
    class Hostile:
        def __getattr__(self, name):
            raise RuntimeError("deleted backend")

    c0 = _counter("program.analysis_missing.cost_analysis")
    m0 = _counter("program.analysis_missing.memory_analysis")
    k0 = _counter("program.analysis_missing.collectives")
    row = programs.record("fast", "k", "fresh", 0.1, compiled=Hostile())
    assert row is not None                   # degraded row, not a crash
    assert set(row["missing"]) == {"cost_analysis", "memory_analysis",
                                   "collectives"}
    assert _counter("program.analysis_missing.cost_analysis") == c0 + 1
    assert _counter("program.analysis_missing.memory_analysis") == m0 + 1
    assert _counter("program.analysis_missing.collectives") == k0 + 1


def test_off_mode_disables_everything(monkeypatch):
    monkeypatch.setenv(programs.ENV_VAR, "off")
    assert not programs.enabled()
    assert programs.record("fast", "k", "fresh", 0.1,
                           compiled=FakeCompiled()) is None
    assert programs.table() == []
    assert programs.model_vs_xla("chunk.x", 100) == "model"
    assert programs.sample_memory(devices=[], force=True) is False


def test_rows_mode_skips_the_analysis_compile(monkeypatch):
    monkeypatch.setenv(programs.ENV_VAR, "rows")
    low = FakeLowered(FakeCompiled(cost=[{"flops": 1.0}], mem=FakeMem()))
    row = programs.record("fast", "k", "fresh", 0.1, lowered=low)
    assert low.compile_calls == 0            # no second compile in rows mode
    assert row["family"] == "fast" and "flops" not in row


def test_deep_mode_compiles_the_lowering_and_times_it():
    low = FakeLowered(FakeCompiled(cost=[{"flops": 3.0,
                                          "bytes accessed": 8.0}],
                                   mem=FakeMem()))
    row = programs.record("fast", "k", "fresh", 0.1, lowered=low)
    assert low.compile_calls == 1
    assert row["bytes_accessed"] == 8.0
    t = obs.registry().snapshot_light()["timers"].get(
        "program.analyze_seconds")
    assert t and t["count"] >= 1


def test_deep_mode_compile_failure_is_a_counted_rung():
    c0 = _counter("program.analysis_missing.compile")
    row = programs.record("fast", "k", "fresh", 0.1,
                          lowered=FakeLowered(Exception("wedged")))
    assert row is not None and "bytes_accessed" not in row
    assert _counter("program.analysis_missing.compile") == c0 + 1


def test_record_loaded_is_the_exported_source():
    row = programs.record_loaded(
        "fast", "sig123",
        FakeCompiled(cost=[{"bytes accessed": 1e4, "flops": 1.0,
                            "transcendentals": 0.0}],
                     mem=FakeMem()))
    assert row["source"] == "exported" and row["compile_s"] == 0.0
    assert row["bytes_accessed"] == 1e4
    assert _counter("program.records.exported") >= 1


# -- drift gate ---------------------------------------------------------------


def _seed_fast_row(xla_bytes=1000.0):
    programs.record("fast", "k", "fresh", 0.1,
                    compiled=FakeCompiled(
                        cost=[{"bytes accessed": xla_bytes,
                               "flops": 1.0, "transcendentals": 0.0}],
                        mem=FakeMem()))


def test_model_vs_xla_within_tolerance_tags_xla():
    _seed_fast_row(1000.0)
    src = programs.model_vs_xla("chunk.s4.e0", 1100)
    assert src == "xla"
    g = obs.registry().snapshot_light()["gauges"]
    assert g["program.model_drift_pct.chunk.s4.e0"] == pytest.approx(
        10.0, abs=0.01)


def test_model_vs_xla_past_tolerance_counts_documented_divergence(
        monkeypatch):
    monkeypatch.setenv("EXAML_DRIFT_TOL_PCT", "25")
    _seed_fast_row(1000.0)
    c0 = _counter("program.model_drift_exceeded.chunk.x")
    src = programs.model_vs_xla("chunk.x", 2000)   # 100% drift
    assert src == "xla"                            # still compiler-backed
    assert _counter("program.model_drift_exceeded.chunk.x") == c0 + 1
    g = obs.registry().snapshot_light()["gauges"]
    assert g["program.model_drift_pct.chunk.x"] == pytest.approx(100.0)


def test_model_vs_xla_without_compiler_figure_stays_model():
    assert programs.model_vs_xla("chunk.x", 500) == "model"
    _seed_fast_row(1000.0)
    assert programs.model_vs_xla("pallas.x", 500) == "xla"  # fast serves it
    assert programs.model_vs_xla("grad.x", 500) == "model"  # no grad row
    assert programs.model_vs_xla("chunk.x", 0) == "model"   # no bytes


def test_tier_families_cover_every_engine_tier():
    assert set(programs.TIER_FAMILIES) >= {
        "scan", "chunk", "pallas", "whole", "universal", "grad"}


# -- live memory sampling -----------------------------------------------------


class FakeDevice:
    def __init__(self, dev_id, stats):
        self.id = dev_id
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def test_sample_memory_gauges_and_cpu_degradation():
    c0 = _counter("program.analysis_missing.memory_stats")
    ok = programs.sample_memory(devices=[
        FakeDevice(0, {"bytes_in_use": 100, "peak_bytes_in_use": 200,
                       "bytes_limit": 1000}),
        FakeDevice(1, None),                 # CPU-style: no stats
    ], force=True)
    assert ok is True
    g = obs.registry().snapshot_light()["gauges"]
    assert g["mem.device.0.in_use"] == 100
    assert g["mem.device.0.peak"] == 200
    assert g["mem.device.0.limit"] == 1000
    assert "mem.device.1.in_use" not in g
    # a stats-less device degrades to the host-RSS gauge (the memory
    # governor's CPU usage signal), not to the missing counter — that
    # only ticks when the RSS fallback is ALSO unavailable
    assert g["mem.host.rss"] > 0
    assert _counter("program.analysis_missing.memory_stats") == c0


def test_host_rss_fallback_reports_live_bytes():
    rss = programs.host_rss_bytes()
    assert rss is not None and rss > 1024 * 1024   # a real process RSS


def test_sample_memory_raising_backend_counts_and_returns_false():
    c0 = _counter("program.analysis_missing.memory_stats")
    assert programs.sample_memory(
        devices=[FakeDevice(0, Exception("backend gone"))],
        force=True) is False
    assert _counter("program.analysis_missing.memory_stats") == c0 + 1


def test_sample_memory_rate_limit(monkeypatch):
    monkeypatch.setenv("EXAML_MEM_SAMPLE_S", "3600")
    dev = [FakeDevice(0, {"bytes_in_use": 1})]
    assert programs.sample_memory(devices=dev) is True
    assert programs.sample_memory(devices=dev) is False   # throttled
    assert programs.sample_memory(devices=dev, force=True) is True


# -- jsonl stream -------------------------------------------------------------


def test_stream_writes_next_to_ledger_and_reads_torn(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("EXAML_LEDGER_DIR", str(tmp_path))
    programs.record("fast", "k1", "fresh", 0.1,
                    compiled=FakeCompiled(cost=[{"flops": 1.0}],
                                          mem=FakeMem()))
    programs.record("scan", "k2", "xla-cache", 0.2,
                    compiled=FakeCompiled())
    programs.reset()                         # close the stream handle
    (path,) = [p for p in os.listdir(tmp_path)
               if p.startswith("programs.p") and p.endswith(".jsonl")]
    with open(tmp_path / path, "a") as f:
        f.write('{"family": "torn...')       # killed-writer torn line
    rows = programs.read_stream(str(tmp_path / path))
    assert [r["family"] for r in rows] == ["fast", "scan"]
    assert programs.read_dir(str(tmp_path)) == rows
    assert programs.read_dir(str(tmp_path / "absent")) == []


def test_snapshot_embeds_the_programs_table():
    programs.record("fast", "k", "fresh", 0.1, compiled=FakeCompiled())
    snap = obs.snapshot()
    assert [r["family"] for r in snap["programs"]] == ["fast"]


# -- engine integration: real dispatches carry both bytes figures ------------


def _tiny_instance():
    from examl_tpu.instance import PhyloInstance
    from examl_tpu.io.alignment import build_alignment_data

    rng = np.random.default_rng(3)
    names = [f"t{i}" for i in range(10)]
    seqs = ["".join("ACGT"[b] for b in rng.integers(0, 4, 300))
            for _ in names]
    inst = PhyloInstance(build_alignment_data(names, seqs))
    return inst, inst.random_tree(0)


def test_engine_dispatches_populate_observatory_with_drift(monkeypatch):
    """The acceptance fixture: chunk-tier (full traversal) and
    scan-tier (Newton smoothing) dispatches on the CPU parity fixture
    leave rows carrying BOTH the analytic model bytes (traffic
    counters) and XLA bytes-accessed, with the drift gauge computed
    per tier."""
    monkeypatch.setenv("EXAML_TRAFFIC_WINDOW_DISPATCHES", "1")
    monkeypatch.setenv("EXAML_TRAFFIC_WINDOW_WALL_S", "0")
    inst, tree = _tiny_instance()
    inst.evaluate(tree, full=True)
    # The second, compile-free traversal is the one whose traffic
    # window can close (windows exclude first-call compiles).
    inst.evaluate(tree, full=True)
    inst.makenewz(tree, tree.start.back, tree.start, tree.start.z,
                  maxiter=2)
    rows = programs.table()
    fams = {r["family"] for r in rows}
    assert "fast" in fams                    # chunk tier
    assert fams & {"newton", "sumtable", "trav_eval", "traverse"}
    with_bytes = [r for r in rows if r.get("bytes_accessed")]
    assert with_bytes, rows                  # compiler truth landed
    assert all(r["source"] in ("fresh", "xla-cache") for r in rows)
    snap = obs.registry().snapshot_light()
    assert snap["counters"]["engine.traffic_bytes"] > 0   # model side
    drift = {k: v for k, v in snap["gauges"].items()
             if k.startswith("program.model_drift_pct.")}
    assert drift, snap["gauges"]             # the gate actually ran
    src = {k: v for k, v in snap["gauges"].items()
           if k.startswith("engine.traffic_source_xla.")}
    assert src and all(v in (0.0, 1.0) for v in src.values())


@pytest.fixture
def export_env(tmp_path, monkeypatch):
    """Isolated persistent cache + export bank ON (test_export_bank's
    isolation pattern); restores the real cache config afterwards."""
    from examl_tpu import config
    from examl_tpu.ops import export_bank

    monkeypatch.setenv("EXAML_COMPILE_CACHE", str(tmp_path / "xla"))
    monkeypatch.setenv("EXAML_EXPORT_BANK", "on")
    assert config.enable_persistent_compilation_cache()
    export_bank.reset()
    yield
    export_bank.reset()
    monkeypatch.delenv("EXAML_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("EXAML_EXPORT_BANK", raising=False)
    config.enable_persistent_compilation_cache()


def test_exported_cold_start_populates_observatory(export_env):
    """Acceptance: a compile-count-free exported start still populates
    the observatory — the deserialized executable answers
    cost_analysis() directly (source "exported", zero compile
    seconds), which is how a zero-compile cold start stays
    observable."""
    import jax
    import jax.numpy as jnp
    from examl_tpu.ops import export_bank

    def impl(x):
        return (x @ x).sum()

    x = jnp.ones((8, 8))
    export_bank.wrap(jax.jit(impl), jax.jit(impl), "toy",
                     ("toy", 0))(x)          # populate the bank
    export_bank.reset()                      # cold-process emulation
    programs.reset()

    def boom(*a):
        raise AssertionError("fallback dispatched — artifact not served")

    out = export_bank.wrap(jax.jit(impl), boom, "toy", ("toy", 0))(x)
    assert float(out) == 512.0
    rows = [r for r in programs.table() if r["source"] == "exported"]
    assert len(rows) == 1 and rows[0]["family"] == "toy"
    assert rows[0]["compile_s"] == 0.0
    assert rows[0].get("bytes_accessed")     # analyses free off the load
    assert _counter("program.records.exported") >= 1


# -- run_report --diff --------------------------------------------------------


def _tools_import(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    return __import__(name)


def _snap(gbps=None, counters=None, timers=None, programs_=None):
    s = {"counters": dict(counters or {}), "gauges": {}, "timers": {}}
    for tier, (v, bound) in (gbps or {}).items():
        s["gauges"][f"engine.achieved_gbps.{tier}"] = v
        s["gauges"][f"engine.regime_dispatch_bound.{tier}"] = bound
    for name, p95 in (timers or {}).items():
        s["timers"][name] = {"count": 10, "total_s": p95 * 10,
                             "min_s": p95, "max_s": p95, "p95_s": p95}
    if programs_:
        s["programs"] = programs_
    return s


def test_diff_snapshots_ok_on_identical():
    run_report = _tools_import("run_report")
    s = _snap(gbps={"chunk.x": (50.0, 0.0)},
              counters={"engine.dispatch_count": 100},
              timers={"dispatch": 0.01})
    lines = []
    assert run_report.diff_snapshots(s, s, out=lines.append) == []
    assert any("DIFF VERDICT: OK" in ln for ln in lines)


def test_diff_snapshots_flags_gbps_drop_and_alarm_growth():
    run_report = _tools_import("run_report")
    old = _snap(gbps={"chunk.x": (50.0, 0.0)},
                counters={"engine.watchdog_barks": 0})
    new = _snap(gbps={"chunk.x": (30.0, 0.0)},              # -40%
                counters={"engine.watchdog_barks": 2})
    lines = []
    findings = run_report.diff_snapshots(old, new, out=lines.append)
    text = "\n".join(lines)
    assert len(findings) == 2
    assert "DIFF VERDICT: REGRESSION" in text
    assert "chunk.x" in text and "watchdog_barks" in text


def test_diff_snapshots_ignores_dispatch_bound_windows_and_noise():
    run_report = _tools_import("run_report")
    old = _snap(gbps={"scan.x": (50.0, 1.0)},    # dispatch-bound: not
                timers={"dispatch": 0.010})      # a bandwidth number
    new = _snap(gbps={"scan.x": (10.0, 1.0)},
                timers={"dispatch": 0.011})      # +10% < 25% tolerance
    assert run_report.diff_snapshots(old, new, out=lambda s: None) == []


def test_diff_snapshots_flags_latency_and_program_bytes_growth():
    run_report = _tools_import("run_report")
    old = _snap(timers={"dispatch": 0.010},
                programs_=[{"family": "fast", "bytes_accessed": 1000}])
    new = _snap(timers={"dispatch": 0.020},
                programs_=[{"family": "fast", "bytes_accessed": 2000}])
    findings = run_report.diff_snapshots(old, new, out=lambda s: None)
    joined = " ".join(findings)
    assert "dispatch" in joined and "fast" in joined
