"""Checkpoint/restart: numbered files, flag compatibility, resume parity,
and the RF convergence criterion."""

import glob

import pytest

from tests.conftest import correlated_dna

from examl_tpu.instance import PhyloInstance
from examl_tpu.search.checkpoint import CheckpointManager
from examl_tpu.search.convergence import RfConvergence, relative_rf
from examl_tpu.search.raxml_search import SearchOptions, compute_big_rapid
from examl_tpu.search.snapshots import topology_key


def test_relative_rf():
    inst = PhyloInstance(correlated_dna(10, 60, seed=3))
    t1 = inst.random_tree(seed=1)
    t2 = inst.random_tree(seed=2)
    k1, k2 = topology_key(t1), topology_key(t2)
    assert relative_rf(k1, k1, 10) == 0.0
    assert 0.0 < relative_rf(k1, k2, 10) <= 1.0


def test_rf_convergence_signals_on_identical_trees():
    inst = PhyloInstance(correlated_dna(10, 60, seed=3))
    t = inst.random_tree(seed=1)
    conv = RfConvergence(10)
    assert not conv(t, "fast", 0)          # first cycle: nothing to compare
    assert conv(t, "fast", 1)              # identical tree: rrf == 0
    t2 = inst.random_tree(seed=2)
    conv2 = RfConvergence(10)
    assert not conv2(t, "fast", 0)
    assert not conv2(t2, "fast", 1)        # different topology: no signal


def test_checkpoint_write_restore_refuses_mismatch(tmp_path):
    inst = PhyloInstance(correlated_dna(10, 80))
    tree = inst.random_tree(seed=0)
    inst.evaluate(tree, full=True)
    mgr = CheckpointManager(str(tmp_path), "run1")
    p1 = mgr.write("FAST_SPRS", {"impr": True}, inst, tree)
    p2 = mgr.write("FAST_SPRS", {"impr": False}, inst, tree)
    assert p1 != p2
    assert len(glob.glob(str(tmp_path / "*.json.gz"))) == 2

    # Same config restores fine.
    inst2 = PhyloInstance(correlated_dna(10, 80))
    tree2 = inst2.random_tree(seed=5)
    resume = CheckpointManager(str(tmp_path), "run1").restore(inst2, tree2)
    assert resume["state"] == "FAST_SPRS"
    assert resume["extras"]["impr"] is False
    assert topology_key(tree2) == topology_key(tree)
    assert inst2.likelihood == pytest.approx(inst.likelihood, abs=1e-6)

    # Different alignment shape must be refused.
    inst3 = PhyloInstance(correlated_dna(10, 90))
    with pytest.raises(ValueError, match="different run configuration"):
        CheckpointManager(str(tmp_path), "run1").restore(
            inst3, inst3.random_tree(seed=1))


def test_checkpoint_counter_resumes_numbering(tmp_path):
    inst = PhyloInstance(correlated_dna(10, 80))
    tree = inst.random_tree(seed=0)
    inst.evaluate(tree, full=True)
    mgr = CheckpointManager(str(tmp_path), "r")
    mgr.write("FAST_SPRS", {}, inst, tree)
    mgr2 = CheckpointManager(str(tmp_path), "r")
    assert mgr2.counter == 1               # continues, never overwrites


@pytest.mark.slow
def test_restart_reaches_continuous_result(tmp_path):
    """Search restarted from a mid-run checkpoint lands at (or above) the
    continuous run's final likelihood (reference restart semantics)."""
    data = correlated_dna(13, 250, seed=11)

    inst = PhyloInstance(data)
    tree = inst.random_tree(seed=4)
    inst.evaluate(tree, full=True)
    mgr = CheckpointManager(str(tmp_path), "cont")
    opts = SearchOptions(initial_set=True, initial=5)
    res = compute_big_rapid(inst, tree, opts,
                            checkpoint_cb=mgr.callback(inst, tree))
    assert mgr.counter >= 2

    # Restart from an intermediate checkpoint (first FAST_SPRS write).
    paths = sorted(glob.glob(str(tmp_path / "*.json.gz")),
                   key=lambda p: int(p.split("ckpt_")[1].split(".")[0]))
    mid = paths[min(1, len(paths) - 1)]
    inst2 = PhyloInstance(data)
    tree2 = inst2.random_tree(seed=99)     # overwritten by restore
    resume = CheckpointManager(str(tmp_path), "cont").restore(
        inst2, tree2, path=mid)
    res2 = compute_big_rapid(inst2, tree2, SearchOptions(
        initial_set=True, initial=5), resume=resume)
    assert res2.likelihood >= res.likelihood - 0.5


def test_rf_history_roundtrip():
    """RF-convergence evidence survives checkpoint serialization: a -D
    restart keeps comparing against the pre-restart cycle (reference
    `restartHashTable.c:279-357`)."""
    inst = PhyloInstance(correlated_dna(10, 60, seed=3))
    t = inst.random_tree(seed=1)
    conv = RfConvergence(10)
    conv(t, "fast", 0)
    blob = conv.to_blob()
    import json
    blob = json.loads(json.dumps(blob))    # through the JSON checkpoint
    conv2 = RfConvergence(10)
    conv2.load_blob(blob)
    # identical tree right after restart -> rrf == 0 -> converged signal
    assert conv2(t, "fast", 1)
    t2 = inst.random_tree(seed=2)
    conv3 = RfConvergence(10)
    conv3.load_blob(blob)
    assert not conv3(t2, "fast", 1)


@pytest.mark.slow
def test_tree_evaluation_mode_restart(tmp_path):
    """-f e writes MOD_OPT checkpoints; a restarted run resumes after the
    last finished tree and reproduces the uninterrupted run's results
    (reference `axml.h:655-659`, dispatch `searchAlgo.c:1730-1749`)."""
    import re

    from examl_tpu.cli.main import main as cli_main
    from examl_tpu.io.bytefile import write_bytefile

    data = correlated_dna(12, 200, seed=5)
    inst = PhyloInstance(data)
    aln = str(tmp_path / "aln.binary")
    write_bytefile(aln, data)
    trees = str(tmp_path / "trees.nwk")
    with open(trees, "w") as f:
        for seed in (1, 2, 3):
            t = inst.random_tree(seed=seed)
            f.write(t.to_newick(data.taxon_names) + "\n")

    w1 = str(tmp_path / "w1")
    assert cli_main(["-s", aln, "-t", trees, "-n", "FULL", "-f", "e",
                     "-w", w1]) == 0
    full_info = open(f"{w1}/ExaML_info.FULL").read()
    full_lnls = re.findall(r"Likelihood tree \d+: (-[\d.]+)", full_info)
    assert len(full_lnls) == 3

    # Interrupted run: evaluate only tree 0 by truncating the input, then
    # restart with the full file from the checkpoint.
    w2 = str(tmp_path / "w2")
    trees1 = str(tmp_path / "first.nwk")
    with open(trees1, "w") as f:
        f.write(open(trees).readline())
    assert cli_main(["-s", aln, "-t", trees1, "-n", "RES", "-f", "e",
                     "-w", w2]) == 0
    assert cli_main(["-s", aln, "-t", trees, "-n", "RES", "-f", "e",
                     "-R", "-w", w2]) == 0
    res_info = open(f"{w2}/ExaML_info.RES").read()
    res_lnls = re.findall(r"Likelihood tree (\d+): (-[\d.]+)", res_info)
    # restart continued at tree 1 and 2 (tree 0 not recomputed)
    assert [i for i, _ in res_lnls].count("0") == 1
    got = {i: float(v) for i, v in res_lnls}
    want = {str(i): float(v) for i, v in enumerate(full_lnls)}
    for i in ("0", "1", "2"):
        assert got[i] == pytest.approx(want[i], abs=0.05), (i, got, want)
    # results file contains all three trees
    out_trees = open(f"{w2}/ExaML_TreeFile.RES").read().strip().split("\n")
    assert len(out_trees) == 3


def test_prune_sweeps_orphans(tmp_path):
    """keep_last pruning removes EVERY stale index, not just the newest
    expired one: orphans from a crash between publish and prune, or from
    a keep_last that shrank across a restart, must not leak."""
    from examl_tpu.search.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), "PR", keep_last=2)
    for i in (0, 1, 3, 4, 7):        # gaps simulate prior crashes
        with open(mgr.path_for(i), "w") as f:
            f.write("x")
    mgr.counter = 8
    mgr._prune()
    import glob as _glob
    left = sorted(_glob.glob(mgr._pattern()))
    assert left == [mgr.path_for(7)], left


@pytest.mark.slow
def test_checkpoint_roundtrip_sharded_sev(tmp_path):
    """Checkpoint written by a SHARDED -S run restores into a fresh
    sharded -S instance and reproduces the stored lnL — the checkpoint
    is layout-independent (host-portable topology + params), so the
    per-device pool regions must rebuild transparently on restore
    (reference layout-independent restart, searchAlgo.c:1586-1648)."""
    from examl_tpu.parallel.sharding import default_site_sharding

    data = correlated_dna(12, 260, seed=3)
    sh = default_site_sharding(8)
    inst = PhyloInstance(data, save_memory=True, sharding=sh,
                         block_multiple=8)
    tree = inst.random_tree(seed=2)
    lnl = float(inst.evaluate(tree, full=True))
    mgr = CheckpointManager(str(tmp_path), "sev")
    mgr.write("FAST_SPRS", {}, inst, tree)

    inst2 = PhyloInstance(data, save_memory=True, sharding=sh,
                          block_multiple=8)
    tree2 = inst2.random_tree(seed=77)
    CheckpointManager(str(tmp_path), "sev").restore(inst2, tree2)
    lnl2 = float(inst2.evaluate(tree2, full=True))
    assert lnl2 == pytest.approx(lnl, abs=1e-6)

    # and a fresh DENSE single-device instance restores the same state:
    # the checkpoint does not bake in pool layout or mesh size
    inst3 = PhyloInstance(data)
    tree3 = inst3.random_tree(seed=55)
    CheckpointManager(str(tmp_path), "sev").restore(inst3, tree3)
    lnl3 = float(inst3.evaluate(tree3, full=True))
    assert lnl3 == pytest.approx(lnl, abs=1e-6)
