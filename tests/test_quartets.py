"""Quartet evaluation (-f q): flavors, grouping parser, output format."""

import re

import numpy as np
import pytest

from examl_tpu.instance import PhyloInstance
from examl_tpu.io.alignment import build_alignment_data
from examl_tpu.search.quartets import (QuartetOptions, compute_quartets,
                                       parse_grouping_file)


@pytest.fixture(scope="module")
def inst8():
    rng = np.random.default_rng(5)
    cur = rng.integers(0, 4, 200)
    seqs = []
    for _ in range(8):
        flip = rng.random(200) < 0.2
        cur = np.where(flip, rng.integers(0, 4, 200), cur)
        seqs.append("".join("ACGT"[c] for c in cur))
    return PhyloInstance(
        build_alignment_data([f"t{i}" for i in range(8)], seqs))


def test_grouping_parser(tmp_path, inst8):
    path = tmp_path / "groups.txt"
    path.write_text("(t0, t1), (t2,t3), (t4), (t5, t6, t7)\n")
    groups = parse_grouping_file(str(path), inst8.alignment.taxon_names)
    assert groups == [[1, 2], [3, 4], [5], [6, 7, 8]]
    bad = tmp_path / "bad.txt"
    bad.write_text("(t0), (t1), (t0), (t2)")
    with pytest.raises(ValueError, match="two groups"):
        parse_grouping_file(str(bad), inst8.alignment.taxon_names)


@pytest.mark.slow
def test_all_quartets_output(tmp_path, inst8):
    tree = inst8.random_tree(seed=1)
    out = str(tmp_path / "q.out")
    n = compute_quartets(inst8, tree, QuartetOptions(epsilon=1.0), out)
    assert n == 70                              # C(8,4)
    lines = [l for l in open(out) if "|" in l]
    assert len(lines) == 210                    # 3 topologies each
    assert all(re.match(r"\d+ \d+ \| \d+ \d+: -\d+\.\d+", l)
               for l in lines)


@pytest.mark.slow
def test_grouped_quartets(tmp_path, inst8):
    gfile = tmp_path / "groups.txt"
    gfile.write_text("(t0,t1),(t2),(t4),(t6,t7)")
    tree = inst8.random_tree(seed=1)
    out = str(tmp_path / "qg.out")
    n = compute_quartets(
        inst8, tree,
        QuartetOptions(grouping_file=str(gfile), epsilon=1.0), out)
    assert n == 2 * 1 * 1 * 2
    lines = [l for l in open(out) if "|" in l]
    assert len(lines) == 12


@pytest.mark.slow
def test_quartet_checkpoint_restart(tmp_path, inst8):
    """Resumed quartet run reproduces the continuous run's output file."""
    from examl_tpu.search.checkpoint import CheckpointManager

    tree = inst8.random_tree(seed=1)
    out = str(tmp_path / "q.out")
    mgr = CheckpointManager(str(tmp_path), "q")
    n = compute_quartets(
        inst8, tree,
        QuartetOptions(epsilon=1.0, checkpoint_interval=30,
                       checkpoint_mgr=mgr), out)
    assert n == 70 and mgr.counter >= 2
    continuous = open(out).read()

    # Restart from the newest checkpoint with a fresh instance: truncates
    # to the checkpointed position and recomputes the tail.
    import numpy as np
    rng = np.random.default_rng(5)
    cur = rng.integers(0, 4, 200)
    seqs = []
    for _ in range(8):
        flip = rng.random(200) < 0.2
        cur = np.where(flip, rng.integers(0, 4, 200), cur)
        seqs.append("".join("ACGT"[c] for c in cur))
    from examl_tpu.io.alignment import build_alignment_data
    inst2 = PhyloInstance(
        build_alignment_data([f"t{i}" for i in range(8)], seqs))
    tree2 = inst2.random_tree(seed=9)
    resume = CheckpointManager(str(tmp_path), "q").restore(inst2, tree2)
    assert resume["state"] == "QUARTETS"
    n2 = compute_quartets(
        inst2, tree2, QuartetOptions(epsilon=1.0, resume=resume), out)
    assert n2 == 70
    resumed = open(out).read()
    assert resumed == continuous


@pytest.mark.slow
def test_random_quartet_sampling(tmp_path, inst8):
    tree = inst8.random_tree(seed=1)
    out = str(tmp_path / "qr.out")
    n = compute_quartets(
        inst8, tree, QuartetOptions(random_samples=10, epsilon=1.0), out)
    assert n >= 10                              # counter includes skipped
    lines = [l for l in open(out) if "|" in l]
    assert len(lines) == 30


@pytest.mark.slow
def test_batched_scorer_matches_sequential(inst8):
    """quartets_batch.score_jobs reproduces the sequential NNI-smoothed
    topology lnLs (same smoothing passes, same Newton semantics)."""
    import io

    from examl_tpu.search import quartets_batch
    from examl_tpu.search.quartets import _three_topologies

    inst = inst8
    tree = inst.random_tree(seed=2)
    inst.evaluate(tree, full=True)
    n = inst.alignment.ntaxa
    q1, q2 = tree.nodep[n + 1], tree.nodep[n + 2]
    sets = [(1, 2, 3, 4), (2, 5, 7, 8), (1, 6, 7, 8)]
    out = io.StringIO()
    for s in sets:
        _three_topologies(inst, tree, q1, q2, *s, out)
    seq = [float(r.split(": ")[1])
           for r in out.getvalue().strip().split("\n")]
    jobs = [j for s in sets for j in quartets_batch.three_topology_jobs(*s)]
    got = quartets_batch.score_jobs(inst, jobs)
    np.testing.assert_allclose(got, seq, rtol=1e-6, atol=5e-4)


@pytest.mark.slow
def test_quartets_sharded_match_single_device(tmp_path):
    """-f q on an 8-device mesh writes the same quartet lnLs as the
    single-device run (the quartets x topologies batches are plain
    GSPMD-sharded programs; reference: quartets evaluated under full MPI
    site distribution, `quartets.c:349-616`)."""
    from examl_tpu.parallel.sharding import default_site_sharding

    from tests.conftest import correlated_dna
    ad = correlated_dna(8, 300, seed=5, mut=0.2)

    outs = []
    for tag, sharding in (("one", None), ("mesh", default_site_sharding(8))):
        inst = PhyloInstance(ad, sharding=sharding,
                             block_multiple=8 if sharding else 1)
        tree = inst.random_tree(seed=1)
        out = str(tmp_path / f"q-{tag}.out")
        n = compute_quartets(inst, tree, QuartetOptions(epsilon=1.0), out)
        assert n == 70
        outs.append(sorted(l for l in open(out) if "|" in l))
    only, mesh = outs
    assert len(only) == len(mesh) == 210
    for a, b in zip(only, mesh):
        ha, va = a.rsplit(":", 1)
        hb, vb = b.rsplit(":", 1)
        assert ha == hb
        assert float(va) == pytest.approx(float(vb), abs=2e-3)
