"""The (sites, tree) likelihood fabric (ISSUE 17 / ROADMAP §7).

The composition contract, pinned four ways:

* **Parity matrix**: f64 lnL across `1x1` / `Sx1` / `1xT` / `SxT`
  fabrics — GAMMA, `-M` per-partition branches and PSR — agrees at the
  same pinned tolerances the 8-way battery (tests/test_sharding.py)
  uses; the batched MeshShard path additionally matches the plain
  BatchEvaluator bit for bit.
* **One collective**: every compiled fabric program's optimized-HLO
  census (obs/programs.py: collective_census) is exactly
  `{"all-reduce": 1}` — the root lnL segment-sum over `sites`, ExaML's
  single Allreduce — with zero all-gather / reduce-scatter /
  collective-permute / all-to-all, and nothing over the tree axis.
* **Flag hygiene**: the CLI's mesh validation names every unsupported
  `(S, T)` combination precisely (SEV x T>1, mesh x fleet-devices,
  mesh x single-device, T>1 without a fleet mode) at argument time,
  and the engine backstops SEV x fabric for API users.
* **Observability**: shape gauges and per-tree-slice dispatch/job
  counters land, so tools/run_report.py and tools/top.py can render
  the fabric (GL005 pins the names both directions).

conftest.py forces 8 virtual CPU devices, so every shape here fits.
"""

import jax
import numpy as np
import pytest

from examl_tpu.instance import PhyloInstance
from examl_tpu.io.alignment import build_alignment_data
from examl_tpu.parallel.sharding import (declared_fabric_specs,
                                         declared_specs,
                                         default_site_sharding,
                                         fabric_sharding, make_fabric_mesh,
                                         parse_mesh_spec)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 (virtual) devices")


def _synth_data(ntaxa=12, nsites=300, seed=7, specs=None):
    rng = np.random.default_rng(seed)
    cur = rng.integers(0, 4, nsites)
    seqs = []
    for _ in range(ntaxa):
        flip = rng.random(nsites) < 0.15
        cur = np.where(flip, rng.integers(0, 4, nsites), cur)
        seqs.append("".join("ACGT"[c] for c in cur))
    return build_alignment_data([f"t{i}" for i in range(ntaxa)], seqs,
                                specs=specs)


@pytest.fixture(scope="module")
def data12():
    return _synth_data()


def _fabric(s, t):
    return fabric_sharding(make_fabric_mesh(s, t))


# -- the fabric's shape algebra ----------------------------------------------


def test_parse_mesh_spec():
    assert parse_mesh_spec("2x2") == (2, 2)
    assert parse_mesh_spec(" 4X1 ") == (4, 1)
    for bad in ("2", "2x2x2", "0x2", "2x-1", "axb"):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)


def test_fabric_mesh_device_budget():
    """An over-subscribed shape fails with the device arithmetic in the
    message, not a reshape traceback."""
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_fabric_mesh(4, 4)


def test_fabric_shape_properties():
    sh = _fabric(2, 2)
    assert sh.is_fabric
    assert sh.site_shards == 2 and sh.tree_shards == 2
    # num_devices is the SITE-axis extent: the tree axis must not
    # inflate block_multiple / SEV divisibility arithmetic.
    assert sh.num_devices == 2
    one_d = default_site_sharding(4)
    assert not one_d.is_fabric
    assert one_d.tree_shards == 1 and one_d.num_devices == 4


def test_declared_specs_roundtrip():
    """The manifest's declared-sharding block is byte-identical whether
    derived from a live fabric or computed device-free (the bank's
    path), and a 1-D mesh declares no fleet leaves."""
    live = declared_specs(_fabric(2, 2))
    assert live == declared_fabric_specs(2, 2)
    assert live["axis_names"] == ["sites", "tree"]
    assert live["mesh_shape"] == [2, 2]
    assert "fleet_jobs" in live["leaf_specs"]
    one_d = declared_specs(default_site_sharding(4))
    assert one_d["tree_shards"] == 1
    assert "fleet_jobs" not in one_d["leaf_specs"]


def test_bank_declared_mesh(monkeypatch):
    """The bank's manifest stamp is device-free and declines (returns
    None) for no-spec / 1x1 / malformed specs — a bad spec is the
    CLI's error to raise, not the bank's."""
    import argparse

    from examl_tpu.ops.bank import _declared_mesh

    ns = lambda m: argparse.Namespace(mesh=m)  # noqa: E731
    monkeypatch.delenv("EXAML_MESH", raising=False)
    assert _declared_mesh(ns(None)) is None
    assert _declared_mesh(ns("1x1")) is None
    assert _declared_mesh(ns("bogus")) is None
    assert _declared_mesh(ns("2x2")) == declared_fabric_specs(2, 2)
    # EXAML_MESH backs the flag; the flag wins.
    monkeypatch.setenv("EXAML_MESH", "4x2")
    assert _declared_mesh(ns(None)) == declared_fabric_specs(4, 2)
    assert _declared_mesh(ns("2x1")) == declared_fabric_specs(2, 1)


# -- flag hygiene: every unsupported (S, T) names itself ----------------------


def test_cli_mesh_flag_errors(tmp_path):
    from examl_tpu.cli.main import main as cli_main

    base = ["-s", str(tmp_path / "missing.binary"), "-n", "X",
            "-w", str(tmp_path)]

    # All mesh validation fires at argparse time (exit 2), before any
    # file load — a dummy -s path proves that ordering too.
    for extra in (["--mesh", "2"],                    # malformed spec
                  ["--mesh", "2x2", "--single-device", "-N", "4"],
                  ["--mesh", "1x2"],                  # T>1, no fleet mode
                  ["--mesh", "2x2", "-S", "-N", "4"],  # SEV x T>1
                  ["--mesh", "2x2", "-N", "4",
                   "--fleet-devices", "2"]):          # fabric owns devices
        with pytest.raises(SystemExit) as ei:
            cli_main(base + extra)
        assert ei.value.code == 2


def test_cli_fleet_sev_error_names_shape(tmp_path, capsys):
    """The blanket fleet -S error names the (S, T) combination that
    cannot compose — the operator sees the mesh router looked and
    declined, not that routing is missing."""
    from examl_tpu.cli.main import main as cli_main

    with pytest.raises(SystemExit):
        cli_main(["-s", str(tmp_path / "missing.binary"), "-n", "X",
                  "-w", str(tmp_path), "-S", "-N", "4"])
    err = capsys.readouterr().err
    assert "(S=1, T=J)" in err
    with pytest.raises(SystemExit):
        cli_main(["-s", str(tmp_path / "missing.binary"), "-n", "X",
                  "-w", str(tmp_path), "-S", "-N", "4", "--mesh", "2x2"])
    err = capsys.readouterr().err
    assert "2x2" in err and "-S" in err


def test_sev_fabric_engine_guard(data12):
    """API users bypassing the CLI hit the engine's backstop: SEV
    pools cannot stack per-job arenas along the tree axis."""
    with pytest.raises(ValueError, match="1x2 fabric"):
        PhyloInstance(data12, save_memory=True, sharding=_fabric(1, 2))
    # Sx1 composes: the site axis divides the SEV pool exactly like a
    # 1-D mesh.
    inst = PhyloInstance(data12, save_memory=True, block_multiple=2,
                         sharding=_fabric(2, 1))
    t = inst.random_tree(seed=3)
    ref = PhyloInstance(data12, save_memory=True)
    assert inst.evaluate(t, full=True) == pytest.approx(
        ref.evaluate(ref.random_tree(seed=3), full=True),
        rel=1e-12, abs=1e-7)


# -- the non-slow representative: 2x2 parity + the one-collective pin --------


def test_fabric_parity_and_single_collective(data12):
    """One 2x2 fabric: solo lnL parity with 1x1, MeshShard batch parity
    with the plain BatchEvaluator, exactly one all-reduce in every
    compiled fabric program, and the shape/slice evidence the report
    renders.  (The full shape x model matrix is the slow battery
    below; CI additionally runs tools/mesh_smoke.py through the real
    CLI.)"""
    from examl_tpu import obs
    from examl_tpu.fleet.shard import MeshShard
    from examl_tpu.obs import programs

    # The whole 1x1 baseline runs BEFORE the observatory reset, so the
    # censused table below holds ONLY fabric-compiled programs (a plain
    # single-device program legitimately carries zero collectives).
    inst1 = PhyloInstance(data12)
    lnl1 = inst1.evaluate(inst1.random_tree(seed=3), full=True)
    ev1 = inst1.batch_evaluator()
    groups1 = {}
    for s in range(3):
        p1 = ev1.prepare(inst1.random_tree(seed=s))
        groups1.setdefault(p1.key, []).append(p1)
    out1 = {key: np.asarray(ev1.eval_batch(g))
            for key, g in groups1.items()}

    obs.reset()
    programs.reset()
    sh = _fabric(2, 2)
    inst = PhyloInstance(data12, block_multiple=2, sharding=sh)
    lnl = inst.evaluate(inst.random_tree(seed=3), full=True)
    assert lnl == pytest.approx(lnl1, rel=1e-12, abs=1e-7)

    ev = inst.batch_evaluator()
    assert isinstance(ev, MeshShard)
    assert ev.site_shards == 2 and ev.tree_shards == 2
    groups = {}
    for s in range(3):
        p = ev.prepare(inst.random_tree(seed=s))
        groups.setdefault(p.key, []).append(p)
    assert groups.keys() == groups1.keys()  # same trees -> same profiles
    for key, g in groups.items():
        out = np.asarray(ev.eval_batch(g))
        np.testing.assert_allclose(out, out1[key], rtol=1e-10, atol=1e-7)

    # The jpad contract: pads are tree-axis multiples, so GSPMD never
    # pads the job axis itself (which would silently replicate rows).
    for pads in ev._jpads.values():
        assert all(p % ev.tree_shards == 0 for p in pads)

    # Exactly one cross-shard collective per compiled fabric program:
    # the site-axis lnL all-reduce, nothing else, and nothing over the
    # tree axis (which would show as a second collective here).
    rows = [r for r in programs.table()
            if r.get("collectives") is not None]
    assert rows, "observatory recorded no analyzed fabric programs"
    for r in rows:
        assert r["collectives"] == {"all-reduce": 1}, \
            (r["family"], r["collectives"])
        assert r["collective_total"] == 1

    # Shape gauges + per-slice counters (the names run_report/top
    # render; GL005 keeps them honest both directions).
    snap = obs.snapshot()
    g, c = snap.get("gauges", {}), snap.get("counters", {})
    assert g.get("engine.mesh_site_shards") == 2
    assert g.get("engine.mesh_tree_shards") == 2
    assert g.get("fleet.mesh_tree_shards") == 2
    assert c.get("fleet.mesh_batches", 0) >= 1
    assert c.get("fleet.mesh_slice_dispatches.t0", 0) >= 1
    assert c.get("fleet.mesh_slice_dispatches.t1", 0) >= 1
    assert c.get("fleet.mesh_slice_jobs.t0", 0) >= 1


# -- the full parity matrix (slow tier; mesh_smoke covers CI cadence) --------


@pytest.mark.slow
def test_parity_matrix_gamma(data12):
    inst1 = PhyloInstance(data12)
    lnl1 = inst1.evaluate(inst1.random_tree(seed=3), full=True)
    for s, t in ((2, 1), (1, 2), (2, 2), (4, 2)):
        inst = PhyloInstance(data12, block_multiple=max(1, s),
                             sharding=_fabric(s, t))
        lnl = inst.evaluate(inst.random_tree(seed=3), full=True)
        assert lnl == pytest.approx(lnl1, rel=1e-12, abs=1e-7), (s, t)


@pytest.mark.slow
def test_parity_matrix_multipartition():
    """-M per-partition branch lengths x two partitions on the fabric."""
    from examl_tpu.io.partitions import parse_partition_file

    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".model",
                                     delete=False) as f:
        f.write("DNA, g1 = 1-150\nDNA, g2 = 151-300\n")
        mp = f.name
    data = _synth_data(specs=parse_partition_file(mp))
    inst1 = PhyloInstance(data, per_partition_branches=True)
    lnl1 = inst1.evaluate(inst1.random_tree(seed=3), full=True)
    for s, t in ((2, 1), (1, 2), (2, 2)):
        inst = PhyloInstance(data, per_partition_branches=True,
                             block_multiple=max(1, s),
                             sharding=_fabric(s, t))
        lnl = inst.evaluate(inst.random_tree(seed=3), full=True)
        assert lnl == pytest.approx(lnl1, rel=1e-12, abs=1e-7), (s, t)


@pytest.mark.slow
def test_parity_matrix_psr(data12):
    inst1 = PhyloInstance(data12, rate_model="PSR")
    lnl1 = inst1.evaluate(inst1.random_tree(seed=3), full=True)
    for s, t in ((2, 1), (1, 2), (2, 2)):
        inst = PhyloInstance(data12, rate_model="PSR",
                             block_multiple=max(1, s),
                             sharding=_fabric(s, t))
        lnl = inst.evaluate(inst.random_tree(seed=3), full=True)
        assert lnl == pytest.approx(lnl1, rel=1e-12, abs=1e-7), (s, t)


@pytest.mark.slow
def test_cli_mesh_run_parity(tmp_path):
    """The real CLI: -N multi-start on --mesh 2x2 vs the 1x1 baseline,
    per-job lnL from the fleet results tables (the same drive
    tools/mesh_smoke.py gives CI, here against the slow tier's full
    assertion budget)."""
    from examl_tpu.cli.main import main as cli_main
    from examl_tpu.io.bytefile import write_bytefile

    data = _synth_data(ntaxa=16, nsites=400)
    write_bytefile(str(tmp_path / "a.binary"), data)

    def run(tag, extra):
        wd = tmp_path / tag
        rc = cli_main(["-s", str(tmp_path / "a.binary"), "-n", tag,
                       "-w", str(wd), "-N", "6"] + extra)
        assert rc == 0
        out = {}
        for line in (wd / f"ExaML_fleet.{tag}").read_text().splitlines():
            if line.startswith("#"):
                continue
            parts = line.split()
            out[parts[0]] = float(parts[5])
        return out

    base = run("B11", [])
    mesh = run("M22", ["--mesh", "2x2"])
    assert base.keys() == mesh.keys()
    # The results table reports lnL at f32 granularity: two f32 ULPs of
    # |lnL| is reporting-precision parity (the f64 bit-level check is
    # the in-process battery above).
    for j in base:
        assert mesh[j] == pytest.approx(
            base[j], abs=max(2e-4, 2 * abs(base[j]) * 2.0 ** -23)), j
