"""Observability subsystem (examl_tpu/obs): registry semantics, trace
JSONL well-formedness, engine counter wiring, CLI --metrics/--trace-events,
and per-process trace artifacts on the 2-process multihost path."""

import json
import os
import time

import numpy as np
import pytest

from examl_tpu import obs
from examl_tpu.obs.metrics import MetricsRegistry


# -- registry semantics ------------------------------------------------------


def test_registry_counter_gauge_timer_semantics():
    reg = MetricsRegistry()
    reg.inc("c")
    reg.inc("c", 2)
    reg.inc("f", 0.25)                 # float increments (compile seconds)
    reg.gauge("g", 7)
    reg.gauge("g", 9)                  # gauges overwrite
    with reg.timer("t"):
        pass
    with reg.timer("t"):
        pass
    reg.observe("t", 1.5)
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3
    assert snap["counters"]["f"] == pytest.approx(0.25)
    assert snap["gauges"]["g"] == 9
    t = snap["timers"]["t"]
    assert t["count"] == 3
    assert t["total_s"] >= 1.5
    assert t["max_s"] >= 1.5 and t["min_s"] <= t["max_s"]
    assert reg.counter("c") == 3 and reg.counter("absent") == 0
    reg.reset()
    assert reg.snapshot() == {"counters": {}, "gauges": {}, "timers": {}}


def test_registry_timer_context_exposes_elapsed():
    reg = MetricsRegistry()
    with reg.timer("t") as tm:
        time.sleep(0.01)
    assert tm.elapsed >= 0.005
    assert reg.snapshot()["timers"]["t"]["total_s"] == pytest.approx(
        tm.elapsed)


def test_registry_collector_runs_at_snapshot_and_unregisters():
    reg = MetricsRegistry()
    calls = []

    def collect():
        calls.append(1)
        reg.gauge("live", len(calls))
        return len(calls) < 2          # unregister after 2nd snapshot

    reg.add_collector(collect)
    assert reg.snapshot()["gauges"]["live"] == 1
    assert reg.snapshot()["gauges"]["live"] == 2
    reg.snapshot()
    assert len(calls) == 2             # dropped after returning False


def test_time_dispatch_records_into_registry():
    before = obs.counter("x")          # unrelated; just exercise facade
    del before
    reg = obs.registry()
    t0 = reg.snapshot()["timers"].get("test.dispatch", {}).get("count", 0)
    best = obs.time_dispatch(lambda: time.sleep(0.001), reps=3, warmup=1,
                             name="test.dispatch")
    assert best >= 0.0005
    t1 = reg.snapshot()["timers"]["test.dispatch"]["count"]
    assert t1 - t0 == 3                # warmup is untimed


# -- trace JSONL -------------------------------------------------------------


def _check_balanced(events):
    """Every B has a matching E per (pid, tid), properly nested."""
    stacks = {}
    for ev in events:
        key = (ev.get("pid"), ev.get("tid"))
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev["name"])
        elif ev["ph"] == "E":
            assert stacks.get(key), f"E without B: {ev}"
            assert stacks[key].pop() == ev["name"], ev
    for key, stack in stacks.items():
        assert not stack, f"unclosed spans on {key}: {stack}"


def test_trace_jsonl_wellformed_and_balanced(tmp_path):
    d = str(tmp_path / "tr")
    path = obs.enable_tracing(d, procid=0)
    try:
        with obs.span("outer", args={"k": 1}):
            with obs.span("inner"):
                pass
        with obs.device_span("engine:fake"):
            pass
        obs.instant("marker", args={"why": "test"})
    finally:
        obs.finalize_tracing()
    # The finalized file is strictly valid Chrome-trace JSON ...
    events = json.loads(open(path).read())
    assert isinstance(events, list) and events
    for ev in events:
        assert ev["ph"] in ("B", "E", "X", "i", "M")
        assert "ts" in ev and "pid" in ev
        if ev["ph"] in ("B", "i", "M"):
            assert "name" in ev
    # ... and the line-by-line reader agrees with the array parse.
    assert len(obs.read_events(path)) == len(events)
    _check_balanced([e for e in events if e["ph"] in ("B", "E")])
    names = {e.get("name") for e in events}
    assert {"outer", "inner", "engine:fake", "marker"} <= names
    # process 0 merged a summary
    summary = json.load(open(os.path.join(d, "summary.json")))
    assert os.path.basename(path) in summary["files"]
    assert summary["spans"]["outer"]["count"] == 1


def test_trace_survives_unfinished_span(tmp_path):
    """A span still open when the writer dies must already be on disk
    (the wedged-compile postmortem artifact: the B line names the guilty
    program)."""
    from examl_tpu.obs import trace as trace_mod

    path = str(tmp_path / "t.jsonl")
    w = trace_mod.TraceWriter(path, procid=0)
    w.event({"ph": "B", "name": "compile:fast", "pid": 0, "tid": 0,
             "ts": 1})
    # no E, no close — simulate a wedged process; the flushed file must
    # still be readable and name the open span.
    events = obs.read_events(path)
    assert events[-1]["name"] == "compile:fast"
    assert events[-1]["ph"] == "B"
    w.close()


# -- engine wiring -----------------------------------------------------------


def _tiny_instance():
    from examl_tpu.instance import PhyloInstance
    from examl_tpu.io.alignment import build_alignment_data

    rng = np.random.default_rng(0)
    names = [f"t{i}" for i in range(10)]
    seqs = ["".join("ACGT"[b] for b in rng.integers(0, 4, 300))
            for _ in names]
    inst = PhyloInstance(build_alignment_data(names, seqs))
    return inst, inst.random_tree(0)


def test_engine_cache_and_dispatch_counters():
    """A full traversal compiles (cache miss) and a recompute of the same
    wave profile hits the shared program cache; every device call counts
    a dispatch."""
    inst, tree = _tiny_instance()
    reg = obs.registry()
    c0 = {k: reg.counter("engine." + k) for k in
          ("cache_hits", "cache_misses", "dispatch_count",
           "compile_count", "traversal_entries")}
    inst.evaluate(tree, full=True)
    c1 = {k: reg.counter("engine." + k) for k in c0}
    assert c1["cache_misses"] > c0["cache_misses"]     # first build
    assert c1["compile_count"] > c0["compile_count"]
    assert c1["dispatch_count"] > c0["dispatch_count"]
    assert c1["traversal_entries"] >= c0["traversal_entries"] + 8
    inst.evaluate(tree, full=True)                     # same profile again
    c2 = {k: reg.counter("engine." + k) for k in c0}
    assert c2["cache_hits"] > c1["cache_hits"]
    assert c2["cache_misses"] == c1["cache_misses"]
    assert reg.counter("engine.compile_seconds") > 0


def test_engine_compile_seconds_per_family_and_arena_gauge():
    inst, tree = _tiny_instance()
    inst.evaluate(tree, full=True)
    inst.makenewz(tree, tree.start.back, tree.start, tree.start.z,
                  maxiter=2)
    snap = obs.snapshot()
    fams = [k for k in snap["counters"] if
            k.startswith("engine.compile_seconds.")]
    assert any(k.endswith(".fast") for k in fams), fams
    assert any(k.endswith(".newton") for k in fams), fams
    (eng,) = inst.engines.values()
    expect = (eng.num_rows * eng.B * eng.lane * eng.R * eng.K
              * np.dtype(eng.storage_dtype).itemsize)
    # gauge names are unique per engine (s<K>.e<ordinal>)
    assert eng._obs_tag.startswith("s4.e")
    assert snap["gauges"]["engine.clv_arena_bytes." + eng._obs_tag] == expect


# -- CLI ---------------------------------------------------------------------


def test_report_phases_zero_total_no_zerodivision(tmp_path, monkeypatch):
    """Satellite fix: all-~0.0s phases with a zero wall total must report
    instead of raising ZeroDivisionError on the percentage line."""
    from examl_tpu.cli import main as cli_main

    files = cli_main.RunFiles(str(tmp_path), "Z")
    files._phases = {"startup": 0.0, "inference": 0.0}
    frozen = files.start_time
    monkeypatch.setattr(cli_main.time, "time", lambda: frozen)
    files.report_phases()              # must not raise
    info = open(files.info_path).read()
    assert "Wall-clock by phase" in info
    assert "startup" in info and "0.0%" in info


def test_cli_metrics_and_trace_artifacts(tmp_path):
    """Acceptance-shaped: a CLI run with --metrics and --trace-events
    leaves (1) a metrics JSON with nonzero dispatch/compile/cache
    counters and (2) a per-process Chrome-trace file with nested
    compile/dispatch spans plus the process-0 summary."""
    from examl_tpu.cli.main import main
    from examl_tpu.instance import PhyloInstance
    from examl_tpu.io.alignment import build_alignment_data
    from examl_tpu.io.bytefile import write_bytefile

    rng = np.random.default_rng(5)
    names = [f"t{i}" for i in range(8)]
    seqs = ["".join("ACGT"[b] for b in rng.integers(0, 4, 200))
            for _ in names]
    data = build_alignment_data(names, seqs)
    bf = str(tmp_path / "tiny.binary")
    write_bytefile(bf, data)
    tree = PhyloInstance(data).random_tree(5)
    tf = str(tmp_path / "tiny.tree")
    open(tf, "w").write(tree.to_newick(names))
    m = str(tmp_path / "m.json")
    tr = str(tmp_path / "tr")

    rc = main(["-s", bf, "-n", "OBS", "-t", tf, "-f", "e",
               "-w", str(tmp_path / "out"), "--metrics", m,
               "--trace-events", tr, "--single-device"])
    assert rc == 0
    snap = json.load(open(m))
    c = snap["counters"]
    assert c["engine.dispatch_count"] > 0
    assert c["engine.compile_seconds"] > 0
    assert c["engine.cache_misses"] > 0 and c["engine.cache_hits"] > 0
    assert any(k.startswith("phase.") for k in snap["timers"])
    events = json.loads(open(os.path.join(tr, "trace.p0.jsonl")).read())
    names_seen = {e.get("name") for e in events}
    assert any(n and n.startswith("compile:") for n in names_seen)
    assert any(n and n.startswith("engine:") for n in names_seen)
    _check_balanced([e for e in events if e["ph"] in ("B", "E")])
    assert os.path.exists(os.path.join(tr, "summary.json"))
    # watchdog/info-file routing is wired: the log sink points at the
    # run info file (exercised for real only when a compile exceeds 180s)
    info = open(tmp_path / "out" / "ExaML_info.OBS").read()
    assert "trace events ->" in info and "metrics snapshot ->" in info


# -- multihost ---------------------------------------------------------------


def test_two_process_trace_files_and_summary_merge(tmp_path):
    """Two OS processes sharing one trace dir (procid via EXAML_PROCID,
    the non-distributed override): each writes its own file named by
    procid, and process 0 merges summary.json at exit — the artifact
    layout of the multihost path without needing multiprocess
    collectives on the CPU backend."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    d = str(tmp_path / "tr")
    code = ("from examl_tpu import obs\n"
            "with obs.span('child_work', args={'p': %d}):\n"
            "    obs.instant('mark')\n")
    procs = []
    for p in (1, 0):                   # proc 0 last: its exit merges both
        env = dict(os.environ, EXAML_PROCID=str(p), EXAML_TRACE_DIR=d,
                   PYTHONPATH=repo)
        procs.append(subprocess.Popen([sys.executable, "-c", code % p],
                                      env=env, cwd=repo))
        procs[-1].wait(timeout=120)
    assert all(pr.returncode == 0 for pr in procs)
    for p in (0, 1):
        events = json.loads(open(os.path.join(
            d, f"trace.p{p}.jsonl")).read())
        assert any(e.get("name") == "child_work" for e in events)
        _check_balanced([e for e in events if e["ph"] in ("B", "E")])
    summary = json.load(open(os.path.join(d, "summary.json")))
    assert set(summary["files"]) == {"trace.p0.jsonl", "trace.p1.jsonl"}
    assert summary["spans"]["child_work"]["count"] == 2


@pytest.mark.slow
def test_multihost_per_process_trace_files(tmp_path, monkeypatch):
    """The 2-process dryrun_multihost path with EXAML_TRACE_DIR set:
    each process writes its own trace file named by procid, both are
    well-formed, and process 0 merges a summary."""
    from __graft_entry__ import dryrun_multihost

    d = str(tmp_path / "tr")
    monkeypatch.setenv("EXAML_TRACE_DIR", d)
    try:
        dryrun_multihost(2, 4)
    except RuntimeError as exc:
        if "Multiprocess computations aren't implemented" in str(exc):
            # This jaxlib build cannot run multi-PROCESS collectives on
            # the CPU backend at all (the whole seed multihost battery
            # fails the same way); the trace-artifact assertion needs a
            # build where the dryrun itself works.
            pytest.skip("jaxlib CPU backend lacks multiprocess "
                        "collectives")
        raise
    files = sorted(os.listdir(d))
    assert "trace.p0.jsonl" in files and "trace.p1.jsonl" in files
    for name in ("trace.p0.jsonl", "trace.p1.jsonl"):
        events = json.loads(open(os.path.join(d, name)).read())
        assert any(e.get("name", "").startswith("engine:")
                   for e in events), name
        _check_balanced([e for e in events if e["ph"] in ("B", "E")])
    assert "summary.json" in files
