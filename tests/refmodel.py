"""Parse the reference's ExaML_modelFile / ExaML_TreeFile outputs.

Test infrastructure for raw-likelihood parity at the reference's optimum
(`printModelParams`, reference `axml.c:1733-1835`): install the printed
alpha / GTR rates / frequencies and the 20-digit branch lengths of
ExaML_TreeFile, then a single evaluate must reproduce the reference's
final lnL — the likelihood surface is at its maximum there, so the
6-decimal rounding of the printed parameters perturbs lnL only at second
order and the comparison is tight.
"""

import re
from dataclasses import dataclass
from typing import List, Optional

RATE_MIN = 1e-7      # reference RATE_MIN (axml.h:167); printed 0.000000
                     # means a rate optimized to the lower bound


@dataclass
class RefPartitionParams:
    name: str
    alpha: Optional[float]
    rates: Optional[List[float]]
    freqs: List[float]
    matrix: Optional[str]      # protein matrix name (AUTO output)


def parse_model_file(path: str) -> List[RefPartitionParams]:
    out: List[RefPartitionParams] = []
    cur = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            m = re.match(r"Model Parameters of Partition \d+, Name: (\S+),", line)
            if m:
                if cur:
                    out.append(cur)
                cur = RefPartitionParams(name=m.group(1), alpha=None,
                                         rates=None, freqs=[], matrix=None)
                continue
            if cur is None:
                continue
            m = re.match(r"alpha: ([\d.eE+-]+)", line)
            if m:
                cur.alpha = float(m.group(1))
                continue
            m = re.match(r"rate\s+\S+\s*<->\s*\S+\s*:\s*([\d.eE+-]+)", line)
            if m:
                if cur.rates is None:
                    cur.rates = []
                cur.rates.append(max(float(m.group(1)), RATE_MIN))
                continue
            m = re.match(r"freq pi\([^)]+\)\s*: ([\d.eE+-]+)", line)
            if m:
                cur.freqs.append(float(m.group(1)))
                continue
            m = re.match(r"Substitution Matrix: (\S+)", line)
            if m:
                cur.matrix = m.group(1)
    if cur:
        out.append(cur)
    return out


def install_reference_params(inst, params: List[RefPartitionParams]) -> None:
    """Overwrite the instance's per-partition models with the reference's
    printed optimum (tests only — rounding is second-order at the optimum)."""
    import numpy as np

    from examl_tpu.models import protein as protein_mod
    from examl_tpu.models.gtr import build_model

    assert len(params) == inst.num_parts, (len(params), inst.num_parts)
    for gid, (part, rp) in enumerate(zip(inst.alignment.partitions, params)):
        freqs = np.asarray(rp.freqs)
        freqs = freqs / freqs.sum()
        # The reference prints the full upper-triangle rate matrix it used
        # (the AUTO-selected one for AUTO partitions), so installing the
        # printed rates is always exact; the matrix label is informational.
        rates = None
        if rp.rates is not None and len(rp.rates) in (6, 190):
            rates = np.asarray(rp.rates)
        elif part.datatype.name == "AA":
            name = rp.matrix or part.model_name
            if name not in ("GTR", "AUTO"):
                rates, _ = protein_mod.get_matrix(name.upper())
        inst.models[gid] = build_model(
            part.datatype, freqs, rates=rates,
            alpha=rp.alpha if rp.alpha is not None else 1.0,
            ncat=inst.ncat, use_median=inst.use_median)
    inst.push_models()
