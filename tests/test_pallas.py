"""Pallas chunk kernels: interpret-mode parity with the XLA fast path.

The fused Mosaic kernels (ops/pallas_newview.py) must be drop-in
replacements for fastpath.run_chunks — same arena contents, same scaler
events — across datatypes and under heavy rescaling.  On CPU they run
through the Pallas interpreter; the TPU numerics of the contained
dot_generals are pinned separately by NUMERICS.md bounds.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from examl_tpu.instance import PhyloInstance  # noqa: E402
from examl_tpu.io.alignment import build_alignment_data  # noqa: E402
from examl_tpu.ops import fastpath, pallas_newview  # noqa: E402


def _instance(datatype, ntaxa, nsites, seed=0):
    rng = np.random.default_rng(seed)
    alphabet = {"AA": "ARNDCQEGHILKMFPSTWYV", "DNA": "ACGT"}[datatype]
    names = [f"t{i}" for i in range(ntaxa)]
    seqs = ["".join(alphabet[c]
                    for c in rng.integers(0, len(alphabet), nsites))
            for _ in names]
    ad = build_alignment_data(names, seqs, datatype_name=datatype)
    return PhyloInstance(ad, dtype=jnp.float32)


def _compare(inst, tree, z_override=None):
    eng = inst.engines[max(inst.engines)]
    _, entries = tree.full_traversal_centroid()
    if z_override is not None:
        from examl_tpu.tree.topology import TraversalEntry
        entries = [TraversalEntry(e.parent, e.left, e.right,
                                  [z_override] * len(e.zl),
                                  [z_override] * len(e.zr))
                   for e in entries]
    sched = eng._fast_schedule(entries)
    ref_clv, ref_sc = fastpath.run_chunks(
        eng.models, eng.block_part, eng.tips, eng.clv, eng.scaler,
        sched.chunks, eng.scale_exp, eng.fast_precision)
    pal_clv, pal_sc = pallas_newview.run_chunks(
        eng.models, eng.block_part, eng.tips, jnp.array(eng.clv),
        jnp.array(eng.scaler), sched.chunks, eng.scale_exp,
        interpret=True)
    np.testing.assert_array_equal(np.asarray(ref_sc), np.asarray(pal_sc))
    np.testing.assert_allclose(np.asarray(ref_clv), np.asarray(pal_clv),
                               rtol=1e-6, atol=1e-7)
    return ref_sc


@pytest.mark.slow
def test_pallas_matches_fastpath_aa():
    inst = _instance("AA", 24, 300)
    _compare(inst, inst.random_tree(1))


def test_pallas_matches_fastpath_dna():
    inst = _instance("DNA", 30, 700)
    _compare(inst, inst.random_tree(2))


@pytest.mark.slow
def test_pallas_scaling_events_match():
    """Short branches force rescale events; the int32 scaler rows must be
    identical (they feed the lnL correction term)."""
    inst = _instance("DNA", 40, 256, seed=3)
    sc = _compare(inst, inst.random_tree(3), z_override=0.05)
    assert int(np.asarray(sc).sum()) > 0     # the test exercised rescaling


def test_engine_full_traversal_pallas(monkeypatch):
    """End to end through the engine: EXAML_PALLAS_INTERPRET routes the
    jitted fast program through the Pallas kernels; lnL must match the
    XLA fast path."""
    inst = _instance("AA", 16, 200, seed=4)
    tree = inst.random_tree(4)
    lnl_ref = inst.evaluate(tree, full=True)

    monkeypatch.setenv("EXAML_PALLAS_INTERPRET", "1")
    inst2 = _instance("AA", 16, 200, seed=4)
    eng2 = inst2.engines[20]
    assert eng2.use_pallas and eng2.pallas_interpret
    tree2 = inst2.random_tree(4)
    lnl_pal = inst2.evaluate(tree2, full=True)
    assert lnl_pal == pytest.approx(lnl_ref, abs=5e-3)


def test_whole_traversal_matches_fastpath():
    """Stage-2 whole-traversal kernel (ops/pallas_whole.py): same CLVs
    and scalers as the chunked fast path, modulo row layout and f32
    rounding from the algebraically-equivalent tip expansion order."""
    from examl_tpu.ops import pallas_whole

    inst = _instance("AA", 24, 300)
    tree = inst.random_tree(1)
    eng = inst.engines[20]
    _, entries = tree.full_traversal_centroid()
    fsched = eng._fast_schedule(entries)
    ref_clv, ref_sc = fastpath.run_chunks(
        eng.models, eng.block_part, eng.tips, jnp.array(eng.clv),
        jnp.array(eng.scaler), fsched.chunks, eng.scale_exp,
        eng.fast_precision)
    wsched = pallas_whole.build_flat(entries, eng.ntips,
                                     eng.num_branch_slots)
    w_clv, w_sc = pallas_whole.run_flat(
        eng.models, eng.block_part, eng.tips, jnp.array(eng.clv),
        jnp.array(eng.scaler), wsched, eng.scale_exp, interpret=True)
    ref_clv, ref_sc = np.asarray(ref_clv), np.asarray(ref_sc)
    w_clv, w_sc = np.asarray(w_clv), np.asarray(w_sc)
    for num, frow in fsched.row_of.items():
        wrow = wsched.row_of[num]
        np.testing.assert_allclose(ref_clv[frow], w_clv[wrow],
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_array_equal(ref_sc[frow], w_sc[wrow])


def test_engine_whole_mode(monkeypatch):
    """EXAML_PALLAS=whole routes full traversals (and the fused
    traverse+evaluate) through the single-kernel path; lnL must match."""
    inst = _instance("DNA", 20, 500, seed=5)
    tree = inst.random_tree(5)
    lnl_ref = inst.evaluate(tree, full=True)

    monkeypatch.setenv("EXAML_PALLAS", "whole")
    monkeypatch.setenv("EXAML_PALLAS_INTERPRET", "1")
    inst2 = _instance("DNA", 20, 500, seed=5)
    eng2 = inst2.engines[4]
    assert eng2.pallas_whole
    tree2 = inst2.random_tree(5)
    lnl_w = inst2.evaluate(tree2, full=True)
    assert lnl_w == pytest.approx(lnl_ref, abs=5e-3)
    # partial traversals after a full one read through the flat row map
    p = tree2.nodep[30]
    inst2.makenewz(tree2, p, p.back, list(p.z), maxiter=8)
    lnl3 = inst2.evaluate(tree2)
    assert lnl3 >= lnl_w - 1e-3
