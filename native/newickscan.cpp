/* Fast newick scanner: one pass over the tree text into flat arrays.
 *
 * Native counterpart of the reference's C newick reader (`treeIO.c:
 * treeReadLen` :798-1030): at the reference's ~120k-taxon ambition
 * (SURVEY §6) a Python character-at-a-time parser takes seconds per
 * tree, and trees are re-read on every restart and tree-evaluation run.
 *
 * Output is an edge list in clade-closing order (children get smaller
 * ids than their parent):
 *   parent[i]  int32   index of node i's parent (-1 for the root)
 *   length[i]  float64 branch length to the parent (NaN if absent)
 *   is_leaf[i] uint8
 *   labels     bytes   '\n'-joined node labels in node-index order
 *
 * CPython C-API module (no pybind11 in this image); examl_tpu/io/newick.py
 * falls back to the pure-Python parser when the extension is unavailable.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Scan {
  std::vector<int32_t> parent;
  std::vector<double> length;
  std::vector<uint8_t> is_leaf;
  std::vector<std::string> label;
  std::string error;
};

inline void skip_ws(const char *s, size_t n, size_t &i) {
  while (i < n && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' ||
                   s[i] == '\r'))
    i++;
}

bool parse_label(const char *s, size_t n, size_t &i, std::string &out) {
  out.clear();
  skip_ws(s, n, i);
  if (i < n && s[i] == '\'') {                 // quoted label
    i++;
    while (i < n) {
      if (s[i] == '\'') {
        if (i + 1 < n && s[i + 1] == '\'') {   // escaped quote
          out.push_back('\'');
          i += 2;
        } else {
          i++;
          return true;
        }
      } else {
        out.push_back(s[i++]);
      }
    }
    return false;                              // unterminated
  }
  while (i < n) {
    char c = s[i];
    if (c == '(' || c == ')' || c == ',' || c == ':' || c == ';' ||
        c == '[')
      break;
    out.push_back(c);
    i++;
  }
  while (!out.empty() && (out.back() == ' ' || out.back() == '\t' ||
                          out.back() == '\n' || out.back() == '\r'))
    out.pop_back();
  return true;
}

bool scan_newick(const char *s, size_t n, Scan &out) {
  std::vector<std::vector<int32_t>> open;   // children of open clades
  std::string label;
  size_t i = 0;
  bool have_current = false;   // a clade just closed, awaiting label/length
  int32_t current = -1;

  auto new_node = [&](bool leaf) -> int32_t {
    int32_t id = (int32_t)out.parent.size();
    out.parent.push_back(-1);
    out.length.push_back(NAN);
    out.is_leaf.push_back(leaf ? 1 : 0);
    out.label.emplace_back();
    return id;
  };

  for (;;) {
    skip_ws(s, n, i);
    if (i < n && s[i] == '(') {
      if (have_current) {
        out.error = "unexpected '(' after clade at " + std::to_string(i);
        return false;
      }
      i++;
      open.emplace_back();
      continue;
    }
    int32_t node;
    if (have_current) {
      node = current;
      have_current = false;
    } else {
      node = new_node(true);
    }
    if (!parse_label(s, n, i, label)) {
      out.error = "unterminated quoted label";
      return false;
    }
    if (!label.empty()) out.label[node] = label;
    skip_ws(s, n, i);
    if (i < n && s[i] == ':') {
      i++;
      skip_ws(s, n, i);
      /* std::from_chars: locale-independent (strtod honors LC_NUMERIC,
       * so a comma-decimal locale would reject valid trees).  It takes
       * no leading '+', which float() accepts -- skip one ourselves.
       * Floating-point from_chars needs libstdc++ >= GCC 11 (libc++ >=
       * LLVM 20); older C++17 toolchains fall back to strtod and keep
       * the (pre-existing) locale caveat rather than failing the
       * build. */
      /* skip the '+' only when a digit or '.' follows: ':+-0.5' must
       * stay a parse error (strtod, float() and the reference reject
       * it), not parse as -0.5 */
      size_t j = i + (i + 1 < n && s[i] == '+'
                      && (std::isdigit((unsigned char)s[i + 1])
                          || s[i + 1] == '.') ? 1 : 0);
      double len = 0.0;
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
      auto res = std::from_chars(s + j, s + n, len);
      bool bad = (res.ec != std::errc() || res.ptr == s + j);
      const char *endp = res.ptr;
#else
      char *endp_m = nullptr;
      len = strtod(s + j, &endp_m);
      bool bad = (endp_m == s + j);
      const char *endp = endp_m;
#endif
      /* both parsers must reject non-finite lengths the same way:
       * out-of-range (1e999) and literal inf/nan forms are parse errors,
       * never silent +/-inf branch lengths in the likelihood code */
      if (bad || !std::isfinite(len)) {
        out.error = "bad branch length at " + std::to_string(i);
        return false;
      }
      out.length[node] = len;
      i = (size_t)(endp - s);
    }

    if (open.empty()) {
      skip_ws(s, n, i);
      if (i < n && s[i] == ';') i++;
      return true;
    }
    open.back().push_back(node);
    skip_ws(s, n, i);
    if (i < n && s[i] == ',') {
      i++;
      continue;
    }
    if (i < n && s[i] == ')') {
      i++;
      int32_t clade = new_node(false);
      for (int32_t c : open.back()) out.parent[c] = clade;
      open.pop_back();
      current = clade;
      have_current = true;
      continue;
    }
    out.error = "expected ',' or ')' at " + std::to_string(i);
    return false;
  }
}

}  // namespace

static PyObject *newickscan_scan(PyObject *, PyObject *args) {
  const char *text;
  Py_ssize_t tn;
  if (!PyArg_ParseTuple(args, "s#", &text, &tn)) return nullptr;

  Scan sc;
  bool ok;
  Py_BEGIN_ALLOW_THREADS
  ok = scan_newick(text, (size_t)tn, sc);
  Py_END_ALLOW_THREADS
  if (!ok) {
    PyErr_SetString(PyExc_ValueError, ("newick: " + sc.error).c_str());
    return nullptr;
  }
  size_t nnodes = sc.parent.size();
  PyObject *labels = PyList_New((Py_ssize_t)nnodes);
  if (!labels) return nullptr;
  for (size_t k = 0; k < nnodes; k++) {
    PyObject *u = PyUnicode_FromStringAndSize(sc.label[k].data(),
                                              (Py_ssize_t)sc.label[k].size());
    if (!u) {
      Py_DECREF(labels);
      return nullptr;
    }
    PyList_SET_ITEM(labels, (Py_ssize_t)k, u);
  }
  PyObject *parent = PyBytes_FromStringAndSize(
      (const char *)sc.parent.data(),
      (Py_ssize_t)(nnodes * sizeof(int32_t)));
  PyObject *length = PyBytes_FromStringAndSize(
      (const char *)sc.length.data(),
      (Py_ssize_t)(nnodes * sizeof(double)));
  PyObject *leaf = PyBytes_FromStringAndSize(
      (const char *)sc.is_leaf.data(), (Py_ssize_t)nnodes);
  return Py_BuildValue("(NNNN)", parent, length, leaf, labels);
}

static PyMethodDef Methods[] = {
    {"scan", newickscan_scan, METH_VARARGS,
     "scan(text) -> (parent_i32_bytes, length_f64_bytes, is_leaf_u8_bytes,"
     " labels_list)"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef Module = {PyModuleDef_HEAD_INIT, "_newickscan",
                                    nullptr, -1, Methods};

PyMODINIT_FUNC PyInit__newickscan(void) { return PyModule_Create(&Module); }
