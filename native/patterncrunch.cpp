/* patterncrunch: native data-loader core for the examl_tpu parser.
 *
 * C++ counterpart of the reference parser's pattern-compression pipeline
 * (`parser/axml.c`: sitesort :1421, sitecombcrunch :1496-1675) — the hot
 * path when converting multi-gigabyte PHYLIP alignments to byteFiles.
 * Exposed to Python through the CPython C API (no pybind11 in this
 * image); built by setup.py as examl_tpu._patterncrunch.
 *
 * compress_columns(codes: uint8[ntaxa, width], C-contiguous)
 *   -> (patterns uint8[ntaxa, npat], weights int64[npat])
 * Duplicate columns collapse into one weighted pattern; pattern order is
 * the lexicographic column order (same canonical order the NumPy path in
 * io/alignment.py produces via np.unique, so outputs are bit-identical).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

/* Minimal NumPy C-API surface via Python calls is too slow for the hot
 * loop; instead we work on raw buffers obtained through the buffer
 * protocol, which every NumPy array supports. */

static PyObject *compress_columns(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *obj;
    if (!PyArg_ParseTuple(args, "O", &obj))
        return nullptr;

    Py_buffer view;
    if (PyObject_GetBuffer(obj, &view,
                           PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) != 0)
        return nullptr;
    if (view.ndim != 2 || view.itemsize != 1) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError,
                        "expected a C-contiguous uint8 matrix");
        return nullptr;
    }
    const Py_ssize_t ntaxa = view.shape[0];
    const Py_ssize_t width = view.shape[1];
    const uint8_t *data = static_cast<const uint8_t *>(view.buf);

    /* Sort column indices lexicographically by column content.  Column j
     * is the byte sequence data[i*width + j], i = 0..ntaxa-1. */
    std::vector<uint32_t> order(static_cast<size_t>(width));
    std::iota(order.begin(), order.end(), 0u);

    auto col_less = [&](uint32_t a, uint32_t b) {
        const uint8_t *pa = data + a, *pb = data + b;
        for (Py_ssize_t i = 0; i < ntaxa; ++i, pa += width, pb += width) {
            if (*pa != *pb)
                return *pa < *pb;
        }
        return false;
    };
    auto col_eq = [&](uint32_t a, uint32_t b) {
        const uint8_t *pa = data + a, *pb = data + b;
        for (Py_ssize_t i = 0; i < ntaxa; ++i, pa += width, pb += width) {
            if (*pa != *pb)
                return false;
        }
        return true;
    };

    Py_BEGIN_ALLOW_THREADS
    std::sort(order.begin(), order.end(), col_less);
    Py_END_ALLOW_THREADS

    /* Run-length encode the sorted columns into unique patterns. */
    std::vector<uint32_t> uniq;
    std::vector<int64_t> weights;
    uniq.reserve(order.size());
    for (size_t k = 0; k < order.size(); ++k) {
        if (k > 0 && col_eq(order[k - 1], order[k])) {
            weights.back() += 1;
        } else {
            uniq.push_back(order[k]);
            weights.push_back(1);
        }
    }
    const Py_ssize_t npat = static_cast<Py_ssize_t>(uniq.size());

    /* Materialize outputs as bytes buffers; the Python wrapper wraps
     * them into NumPy arrays without copying. */
    PyObject *pat_bytes = PyBytes_FromStringAndSize(nullptr, ntaxa * npat);
    PyObject *wgt_bytes =
        PyBytes_FromStringAndSize(nullptr, npat * (Py_ssize_t)sizeof(int64_t));
    if (!pat_bytes || !wgt_bytes) {
        Py_XDECREF(pat_bytes);
        Py_XDECREF(wgt_bytes);
        PyBuffer_Release(&view);
        return nullptr;
    }
    uint8_t *pat = reinterpret_cast<uint8_t *>(PyBytes_AS_STRING(pat_bytes));
    std::memcpy(PyBytes_AS_STRING(wgt_bytes), weights.data(),
                weights.size() * sizeof(int64_t));

    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < ntaxa; ++i) {
        const uint8_t *row = data + i * width;
        uint8_t *out = pat + i * npat;
        for (Py_ssize_t k = 0; k < npat; ++k)
            out[k] = row[uniq[static_cast<size_t>(k)]];
    }
    Py_END_ALLOW_THREADS

    PyBuffer_Release(&view);
    PyObject *result = Py_BuildValue("(NNn)", pat_bytes, wgt_bytes, npat);
    return result;
}

static PyMethodDef Methods[] = {
    {"compress_columns", compress_columns, METH_VARARGS,
     "Collapse duplicate alignment columns into weighted unique patterns."},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_patterncrunch",
    "Native pattern-compression core (reference parser sitesort/"
    "sitecombcrunch equivalent).",
    -1, Methods, nullptr, nullptr, nullptr, nullptr,
};

PyMODINIT_FUNC PyInit__patterncrunch(void)
{
    return PyModule_Create(&moduledef);
}

}  /* extern "C" */
